"""Sequence / transformer layers: embed, layernorm, mha, ffn, seqfc, add,
lmloss.

TPU-idiomatic extension beyond the reference (which has no sequence axis —
fixed image tensors, /root/reference/src/layer/layer.h:33-39; SURVEY §5
"long-context: N/A"): these layers make attention models expressible in the
same config dialect, with tensor-parallel PartitionSpecs over the mesh
'model' axis (heads for attention, hidden for the FFN) and attention
implementations from cxxnet_tpu.ops (reference / chunked online-softmax /
Pallas flash). Ring-attention sequence parallelism over a 'seq' axis lives
in cxxnet_tpu.parallel.ring and shares the same math.

Node convention for sequences: logical shape3 ``(E, S, 1)`` -> array
``(batch, S, 1, E)`` (tokens on the y axis, features on the channel axis,
consistent with the framework's NHWC image convention). Token-id inputs are
flat nodes ``(1, 1, S)``.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..ops.attention import (attention_reference, chunked_attention,
                             flash_attention, rope)
from .base import Layer, Shape3, register_layer
from .loss import LossLayerBase


def _seq(x: jax.Array) -> jax.Array:
    """(b, S, 1, E) -> (b, S, E)."""
    return x.reshape(x.shape[0], x.shape[1], x.shape[3])


def _unseq(x: jax.Array) -> jax.Array:
    """(b, S, E) -> (b, S, 1, E)."""
    return x.reshape(x.shape[0], x.shape[1], 1, x.shape[2])


@register_layer("embed")
class EmbedLayer(Layer):
    """Token embedding: flat id node (1,1,S) -> sequence node (E,S,1).
    ``nhidden`` = embedding dim, ``vocab_size`` = table rows."""
    has_params = True

    def set_param(self, name, val):
        if name == "vocab_size":
            self.vocab_size = int(val)

    def __init__(self, spec, global_cfg):
        self.vocab_size = 0
        super().__init__(spec, global_cfg)
        if self.vocab_size <= 0:
            raise ValueError(f"embed layer {spec.name!r} needs vocab_size")

    def infer_shapes(self, in_shapes: List[Shape3]) -> List[Shape3]:
        self.check_n(in_shapes, 1, 1)
        c, y, S = in_shapes[0]
        if c != 1 or y != 1:
            raise ValueError("embed expects a flat (1,1,S) token-id node")
        return [(self.hp.num_hidden, S, 1)]

    def init_params(self, key, in_shapes):
        return {"wmat": self.hp.init_weight(
            key, (self.vocab_size, self.hp.num_hidden),
            self.vocab_size, self.hp.num_hidden)}

    def apply(self, params, state, inputs, ctx):
        x = inputs[0]
        ids = x.reshape(x.shape[0], -1).astype(jnp.int32)
        out = jnp.take(params["wmat"].astype(ctx.compute_dtype), ids, axis=0)
        return [_unseq(out)], state


@register_layer("layernorm")
class LayerNormLayer(Layer):
    """LayerNorm over the feature axis of a sequence node. Params are keyed
    gamma/beta, which the optimizer scopes into the 'bias' hyper group (so
    weight decay does not pull the multiplicative gamma toward 0)."""
    has_params = True

    def set_param(self, name, val):
        if name == "eps":
            self.eps = float(val)

    def __init__(self, spec, global_cfg):
        self.eps = 1e-5
        super().__init__(spec, global_cfg)

    def infer_shapes(self, in_shapes):
        self.check_n(in_shapes, 1, 1)
        return [in_shapes[0]]

    def init_params(self, key, in_shapes):
        e = in_shapes[0][0]
        return {"gamma": jnp.ones((e,), jnp.float32),
                "beta": jnp.zeros((e,), jnp.float32)}

    def apply(self, params, state, inputs, ctx):
        x = inputs[0].astype(jnp.float32)
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + self.eps)
        y = y * params["gamma"] + params["beta"]
        return [y.astype(ctx.compute_dtype)], state


@register_layer("posembed")
class PosEmbedLayer(Layer):
    """Learned absolute position embedding added to a sequence node
    (E,S,1) -> (E,S,1). Alternative to rotary (``rope = 1`` on mha)."""
    has_params = True

    def infer_shapes(self, in_shapes):
        self.check_n(in_shapes, 1, 1)
        return [in_shapes[0]]

    def init_params(self, key, in_shapes):
        e, s, _ = in_shapes[0]
        return {"wmat": self.hp.init_sigma *
                jax.random.normal(key, (s, e), jnp.float32)}

    def apply(self, params, state, inputs, ctx):
        x = inputs[0]
        pe = params["wmat"].astype(ctx.compute_dtype)
        s_local = x.shape[1]
        if ctx.seq_axis is not None and s_local != pe.shape[0]:
            # sequence parallelism: the table is replicated but this shard
            # holds tokens at a global offset — same offset arithmetic as
            # the mha rope path
            off = jax.lax.axis_index(ctx.seq_axis) * s_local
            pe = jax.lax.dynamic_slice_in_dim(pe, off, s_local, axis=0)
        return [x + pe.reshape(1, s_local, 1, pe.shape[1])], state


class _SeqLinearMixin:
    """Shared init for (in_dim -> out_dim) projections on sequence nodes."""

    def _linear_params(self, key, in_dim, out_dim, no_bias):
        p = {"wmat": self.hp.init_weight(key, (in_dim, out_dim),
                                         in_dim, out_dim)}
        if not no_bias:
            p["bias"] = jnp.full((out_dim,), self.hp.init_bias, jnp.float32)
        return p


@register_layer("mha")
class MultiHeadAttentionLayer(Layer, _SeqLinearMixin):
    """Multi-head self-attention on a sequence node (E,S,1) -> (E,S,1).

    Config: ``nhead``, ``causal`` (0/1), ``attn_impl`` in
    {auto, ref, chunked, flash}, ``attn_block`` (flash/chunked block size).
    Tensor parallelism: q/k/v projections shard over heads on the mesh
    'model' axis, the output projection contracts over sharded heads — the
    TPU-native generalization of the reference's fullc_gather hybrid
    (/root/reference/src/updater/async_updater-inl.hpp:68-94).
    """
    has_params = True

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = bool(int(val))
        elif name == "attn_impl":
            if val not in ("auto", "ref", "chunked", "flash"):
                raise ValueError(f"unknown attn_impl {val!r}")
            self.attn_impl = val
        elif name == "attn_block":
            self.attn_block = int(val)
        elif name == "rope":
            self.rope = bool(int(val))
        elif name == "rope_theta":
            self.rope_theta = float(val)

    def __init__(self, spec, global_cfg):
        self.nhead = 8
        self.causal = False
        self.attn_impl = "auto"
        self.attn_block = 128
        self.rope = False
        self.rope_theta = 10000.0
        super().__init__(spec, global_cfg)

    def infer_shapes(self, in_shapes):
        self.check_n(in_shapes, 1, 1)
        e, s, _ = in_shapes[0]
        if e % self.nhead:
            raise ValueError(
                f"mha {self.name!r}: dim {e} not divisible by nhead {self.nhead}")
        return [in_shapes[0]]

    def init_params(self, key, in_shapes):
        e = in_shapes[0][0]
        h, d = self.nhead, e // self.nhead
        ks = jax.random.split(key, 4)
        p = {}
        for i, nm in enumerate(("q", "k", "v")):
            sub = self._linear_params(ks[i], e, e, self.hp.no_bias)
            sub["wmat"] = sub["wmat"].reshape(e, h, d)
            if "bias" in sub:
                sub["bias"] = sub["bias"].reshape(h, d)
            p[nm] = sub
        out = self._linear_params(ks[3], e, e, self.hp.no_bias)
        out["wmat"] = out["wmat"].reshape(h, d, e)
        p["o"] = out
        return p

    def param_pspecs(self):
        qkv = {"wmat": (None, "model", None), "bias": ("model", None)}
        return {"q": qkv, "k": qkv, "v": qkv,
                "o": {"wmat": ("model", None, None), "bias": None}}

    def _attend(self, q, k, v, ctx):
        if ctx.seq_axis is not None:
            if ctx.seq_gather_kv:
                # pipeline-parallel stage: one k/v all-gather (safe inside
                # the stage's switch branch) instead of the ring
                from ..ops.attention import gather_kv_attention
                return gather_kv_attention(q, k, v, axis_name=ctx.seq_axis,
                                           causal=self.causal)
            # sequence-parallel step (shard_map): q/k/v are local sequence
            # shards; the ring carries k/v around the mesh axis
            from ..parallel.ring import ring_attention
            return ring_attention(q, k, v, axis_name=ctx.seq_axis,
                                  causal=self.causal)
        if self.attn_impl == "ref":
            return attention_reference(q, k, v, causal=self.causal)
        if self.attn_impl == "chunked":
            return chunked_attention(q, k, v, causal=self.causal,
                                     block_k=self.attn_block)
        if self.attn_impl == "flash":
            return flash_attention(q, k, v, causal=self.causal,
                                   block_q=self.attn_block,
                                   block_k=self.attn_block)
        # auto: flash on TPU when the sequence tiles evenly, plain reference
        # for short sequences, chunked otherwise
        S = q.shape[1]
        if jax.default_backend() == "tpu" and S % self.attn_block == 0:
            return flash_attention(q, k, v, causal=self.causal,
                                   block_q=self.attn_block,
                                   block_k=self.attn_block)
        if S <= 512:
            return attention_reference(q, k, v, causal=self.causal)
        return chunked_attention(q, k, v, causal=self.causal,
                                 block_k=self.attn_block)

    def apply(self, params, state, inputs, ctx):
        x = _seq(inputs[0]).astype(ctx.compute_dtype)

        def proj(nm):
            w = params[nm]["wmat"].astype(ctx.compute_dtype)
            out = jnp.einsum("bse,ehd->bshd", x, w)
            if "bias" in params[nm]:
                out = out + params[nm]["bias"].astype(ctx.compute_dtype)
            return out

        q, k, v = proj("q"), proj("k"), proj("v")
        if self.rope:
            off = 0
            if ctx.seq_axis is not None:   # global positions for local shard
                off = jax.lax.axis_index(ctx.seq_axis) * q.shape[1]
            q, k = rope(q, self.rope_theta, off), rope(k, self.rope_theta, off)
        o = self._attend(q, k, v, ctx)
        wo = params["o"]["wmat"].astype(ctx.compute_dtype)
        y = jnp.einsum("bshd,hde->bse", o, wo)
        if "bias" in params["o"]:
            y = y + params["o"]["bias"].astype(ctx.compute_dtype)
        return [_unseq(y)], state


@register_layer("ffn")
class FFNLayer(Layer, _SeqLinearMixin):
    """Position-wise feed-forward (E,S,1) -> (E,S,1); ``nhidden`` = inner
    dim, ``act`` in {gelu, relu}. TP: inner dim sharded over 'model'."""
    has_params = True

    def set_param(self, name, val):
        if name == "act":
            if val not in ("gelu", "relu"):
                raise ValueError(f"unknown ffn act {val!r}")
            self.act = val

    def __init__(self, spec, global_cfg):
        self.act = "gelu"
        super().__init__(spec, global_cfg)

    def infer_shapes(self, in_shapes):
        self.check_n(in_shapes, 1, 1)
        return [in_shapes[0]]

    def init_params(self, key, in_shapes):
        e = in_shapes[0][0]
        f = self.hp.num_hidden or 4 * e
        k1, k2 = jax.random.split(key)
        return {"h": self._linear_params(k1, e, f, self.hp.no_bias),
                "o": self._linear_params(k2, f, e, self.hp.no_bias)}

    def param_pspecs(self):
        return {"h": {"wmat": (None, "model"), "bias": ("model",)},
                "o": {"wmat": ("model", None), "bias": None}}

    def apply(self, params, state, inputs, ctx):
        x = _seq(inputs[0]).astype(ctx.compute_dtype)
        h = jnp.einsum("bse,ef->bsf", x,
                       params["h"]["wmat"].astype(ctx.compute_dtype))
        if "bias" in params["h"]:
            h = h + params["h"]["bias"].astype(ctx.compute_dtype)
        h = jax.nn.gelu(h) if self.act == "gelu" else jax.nn.relu(h)
        y = jnp.einsum("bsf,fe->bse", h,
                       params["o"]["wmat"].astype(ctx.compute_dtype))
        if "bias" in params["o"]:
            y = y + params["o"]["bias"].astype(ctx.compute_dtype)
        return [_unseq(y)], state


@register_layer("seqfc")
class SeqFCLayer(Layer, _SeqLinearMixin):
    """Per-position linear projection (E,S,1) -> (K,S,1), e.g. the LM head.
    ``nhidden`` = K."""
    has_params = True

    def infer_shapes(self, in_shapes):
        self.check_n(in_shapes, 1, 1)
        e, s, _ = in_shapes[0]
        return [(self.hp.num_hidden, s, 1)]

    def init_params(self, key, in_shapes):
        e = in_shapes[0][0]
        return self._linear_params(key, e, self.hp.num_hidden, self.hp.no_bias)

    def param_pspecs(self):
        return {"wmat": (None, "model"), "bias": ("model",)}

    def apply(self, params, state, inputs, ctx):
        x = _seq(inputs[0])
        if "wmat_scale" in params:
            # PTQ-derived int8 weights (quant/ptq.py): positions fold
            # into rows so the projection runs as one int8 matmul with
            # the fused dequant/bias epilogue (ops/fused_quant.py)
            from ..ops.fused_quant import int8_matmul
            b, s, e = x.shape
            y2 = int8_matmul(x.reshape(b * s, e), params["wmat"],
                             params["wmat_scale"], params["act_scale"],
                             params.get("bias"), "none",
                             fused=ctx.fused, spmd=None)
            return [_unseq(y2.reshape(b, s, -1))], state
        x = x.astype(ctx.compute_dtype)
        y = jnp.einsum("bse,ek->bsk", x,
                       params["wmat"].astype(ctx.compute_dtype))
        if "bias" in params:
            y = y + params["bias"].astype(ctx.compute_dtype)
        return [_unseq(y)], state


@register_layer("add")
class AddLayer(Layer):
    """Elementwise sum of N same-shape nodes (residual connections).
    The DAG dialect already allows one node to feed several layers (the
    functional executor has no buffer aliasing), so x + f(x) is
    ``layer[x,fx->y] = add``."""

    def infer_shapes(self, in_shapes):
        if len(in_shapes) < 2 or len(self.spec.nindex_out) != 1:
            raise ValueError(f"add layer {self.name!r} needs >=2 inputs, 1 output")
        for s in in_shapes[1:]:
            if s != in_shapes[0]:
                raise ValueError(
                    f"add layer {self.name!r}: shape mismatch {in_shapes}")
        return [in_shapes[0]]

    def apply(self, params, state, inputs, ctx):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out], state


@register_layer("lmloss")
class LMLossLayer(LossLayerBase):
    """Per-token softmax cross-entropy for language modeling: logits node
    (V,S,1) vs a label slice of width S (token ids). Forward emits per-token
    **log**-probabilities (log_softmax: numerically exact where probs would
    underflow f32, so confidently-wrong tokens keep their gradient; argmax
    metrics are unaffected); loss = masked mean NLL over all tokens."""

    def apply(self, params, state, inputs, ctx):
        x = inputs[0]                              # (b, S, 1, V)
        logits = x.astype(jnp.float32)
        return [jax.nn.log_softmax(logits, axis=-1)], state

    def loss(self, outputs, label, mask):
        logp_all = outputs[0]                      # (b, S, 1, V) log-probs
        b, S = logp_all.shape[0], logp_all.shape[1]
        lp2 = logp_all.reshape(b, S, -1)
        idx = label.astype(jnp.int32)              # (b, S)
        logp = jnp.take_along_axis(lp2, idx[:, :, None], axis=2)[:, :, 0]
        per_example = -jnp.mean(logp, axis=1)      # mean over tokens
        return self._mean(per_example, mask)
