"""High-level wrapper API: ``Net`` / ``DataIter`` / ``train``.

Mirrors the reference Python binding surface (wrapper/cxxnet.py:67-314,
itself a ctypes skin over the C API in wrapper/cxxnet_wrapper.h:36-232):

* ``DataIter(cfg)`` — an iterator handle created from a config *string*
  (CXNIOCreateFromConfig), with ``next()/before_first()/get_data()/
  get_label()/check_valid()`` cursor semantics matching IIterator.
* ``Net(dev, cfg)`` — a net handle (CXNNetCreate) with ``set_param``,
  ``init_model``, ``save_model/load_model``, ``start_round``, ``update``
  (from a DataIter *or* raw numpy arrays, CXNNetUpdateBatch),
  ``evaluate``, ``predict``, ``extract``, ``get_weight``/``set_weight``.
* ``train(cfg, data, label, num_round, param, eval_data)`` — the
  convenience loop (wrapper/cxxnet.py:288-314).

Layout note: the reference's raw-numpy entry points take NCHW float32
(batch, channel, height, width — wrapper/cxxnet.py:165-167). This framework
computes in NHWC (the TPU-friendly layout), so raw arrays are accepted in
NCHW by default for drop-in compatibility and transposed on entry; pass
``layout='NHWC'`` to skip the transpose. Flat 2-D ``(batch, features)``
arrays are accepted directly.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import ConfigPairs, parse_config_string
from .io.data import DataBatch, create_iterator
from .trainer import Trainer

__all__ = ["DataIter", "Net", "train", "create_engine", "engine_predict"]


def _to_nhwc(data: np.ndarray, layout: str) -> np.ndarray:
    """Accept (b,c,h,w) [reference convention], (b,h,w,c) or (b,k)."""
    data = np.asarray(data, dtype=np.float32)
    if data.ndim == 2:
        return data.reshape(data.shape[0], 1, 1, data.shape[1])
    if data.ndim != 4:
        raise ValueError(
            "need a 4-D (batch,channel,y,x) or 2-D (batch,features) array, "
            f"got shape {data.shape}")
    if layout.upper() == "NCHW":
        return np.transpose(data, (0, 2, 3, 1))
    if layout.upper() == "NHWC":
        return data
    raise ValueError(f"unknown layout {layout!r}")


def _to_label(label: np.ndarray, batch: int) -> np.ndarray:
    label = np.asarray(label, dtype=np.float32)
    if label.ndim == 1:
        label = label.reshape(-1, 1)
    if label.ndim != 2 or label.shape[0] != batch:
        raise ValueError(
            f"label must be (batch,) or (batch,width); got {label.shape} "
            f"for batch {batch}")
    return label


class DataIter:
    """Iterator handle with the reference cursor protocol
    (wrapper/cxxnet.py:70-106): ``next()`` advances and returns bool,
    ``value`` is the current DataBatch, ``before_first()`` rewinds."""

    def __init__(self, cfg: Union[str, ConfigPairs]):
        pairs = parse_config_string(cfg) if isinstance(cfg, str) else list(cfg)
        self._iter = create_iterator(pairs)
        self.value: Optional[DataBatch] = None
        self.head = True
        self.tail = False

    def next(self) -> bool:
        if self.head:
            self._iter.before_first()
        self.head = False
        self.value = self._iter.next()
        self.tail = self.value is None
        return not self.tail

    def before_first(self) -> None:
        self._iter.before_first()
        self.value = None
        self.head, self.tail = True, False

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError(
                "iterator at head state, call next() to get to valid state")
        if self.tail:
            raise RuntimeError("iterator reached end")

    def get_data(self) -> np.ndarray:
        self.check_valid()
        return self.value.data

    def get_label(self) -> np.ndarray:
        self.check_valid()
        return self.value.label

    def __iter__(self):
        # whole-epoch iteration (used by Net.evaluate / predict over an iter)
        self.before_first()
        while self.next():
            yield self.value
        self.before_first()


class Net:
    """Net handle (reference WrapperNet, wrapper/cxxnet_wrapper.cpp:79-257).

    Config can be given at construction and/or via ``set_param`` before
    ``init_model``; later ``set_param`` calls on schedule-style keys are
    accepted but only affect a rebuilt net (matching the reference, where
    SetParam after init only touches runtime knobs).
    """

    def __init__(self, dev: str = "", cfg: Union[str, ConfigPairs] = "",
                 layout: str = "NCHW"):
        self._cfg: List[Tuple[str, str]] = (
            parse_config_string(cfg) if isinstance(cfg, str) else list(cfg))
        if dev:
            self._cfg.append(("dev", dev))
        self._layout = layout
        self._trainer: Optional[Trainer] = None

    # -- config / lifecycle -------------------------------------------------
    def set_param(self, name, value) -> None:
        self._cfg.append((str(name), str(value)))

    def _require(self) -> Trainer:
        if self._trainer is None:
            raise RuntimeError("call init_model() (or load_model) first")
        return self._trainer

    def _build(self) -> Trainer:
        if self._trainer is None:
            self._trainer = Trainer(self._cfg)
        return self._trainer

    def init_model(self) -> None:
        self._build().init_model()

    def load_model(self, fname: str) -> None:
        # Trainer.load_model fully populates params/opt state from the
        # checkpoint, so no (discarded) random init_model pass is needed.
        self._build().load_model(fname)

    def save_model(self, fname: str) -> None:
        self._require().save_model(fname)

    def copy_model_from(self, fname: str) -> None:
        """Finetune-style name-matched weight copy (reference CopyModelFrom)."""
        self._require().copy_model_from(fname)

    def start_round(self, round_counter: int) -> None:
        self._require().start_round(round_counter)

    # -- data plumbing ------------------------------------------------------
    def _as_batch(self, data, label=None) -> DataBatch:
        if isinstance(data, DataBatch):
            return data
        arr = _to_nhwc(data, self._layout)
        if label is None:
            lab = np.zeros((arr.shape[0], 1), np.float32)
        else:
            lab = _to_label(label, arr.shape[0])
        return DataBatch(data=arr, label=lab)

    # -- training / inference ----------------------------------------------
    def update(self, data, label=None) -> None:
        """One update step from a DataIter's current batch or raw arrays
        (reference CXNNetUpdateIter / CXNNetUpdateBatch)."""
        tr = self._require()
        if isinstance(data, DataIter):
            data.check_valid()
            tr.update(data.value)
        else:
            if label is None and not isinstance(data, DataBatch):
                raise ValueError("need label to update from a raw array")
            tr.update(self._as_batch(data, label))

    def evaluate(self, data, name: str) -> str:
        """Evaluate over a full iterator; returns the reference's
        ``\\tname-metric:value`` log fragment."""
        tr = self._require()
        if isinstance(data, DataIter):
            return tr.evaluate(iter(data), name)
        return tr.evaluate(data, name)

    def predict(self, data, label=None) -> np.ndarray:
        """Prediction (argmax class / raw scalar). DataIter → current batch,
        matching CXNNetPredictIter; ndarray → that batch."""
        tr = self._require()
        if isinstance(data, DataIter):
            data.check_valid()
            return tr.predict(data.value)
        return tr.predict(self._as_batch(data, label))

    def predict_raw(self, data) -> np.ndarray:
        tr = self._require()
        if isinstance(data, DataIter):
            data.check_valid()
            return tr.predict_raw(data.value)
        return tr.predict_raw(self._as_batch(data))

    def extract(self, data, name: str) -> np.ndarray:
        """Extract a named node's activations ('top' = last node)."""
        tr = self._require()
        if isinstance(data, DataIter):
            data.check_valid()
            return tr.extract_feature(data.value, name)
        return tr.extract_feature(self._as_batch(data), name)

    # -- weights ------------------------------------------------------------
    def get_weight(self, layer_name: str, tag: str = "wmat"):
        tr = self._require()
        try:
            return tr.get_weight(layer_name, tag)
        except KeyError:
            return None     # reference returns NULL/odim=0 for missing

    def set_weight(self, weight: np.ndarray, layer_name: str,
                   tag: str = "wmat") -> None:
        self._require().set_weight(np.asarray(weight, np.float32),
                                   layer_name, tag)

    @property
    def trainer(self) -> Trainer:
        """Escape hatch to the full Trainer API."""
        return self._require()

    # -- serving ------------------------------------------------------------
    def create_engine(self, **kw):
        """Wrap this net's trained params into a serve.InferenceEngine
        (bucketed compile cache + predict/predict_raw/extract) — the
        online-serving capability the C API never had. Keyword args pass
        through (buckets, max_batch, cache_size, stats, dtype — the
        serving compute dtype, e.g. dtype='bfloat16' to serve an
        fp32-trained model at the bf16 matmul rate with fp32 outputs)."""
        from .serve.engine import InferenceEngine
        kw.setdefault("layout", self._layout)
        return InferenceEngine(self._require(), **kw)


def create_engine(cfg: Union[str, ConfigPairs], model_path: str,
                  dev: str = "", layout: str = "NCHW", **kw):
    """One-call engine construction from a net config + checkpoint:
    optimizer state is stripped before device placement
    (checkpoint.load_for_inference). ``dtype='bfloat16'`` (kw) serves
    the fp32 master weights at a reduced compute dtype — checkpoints
    are policy-portable, so any checkpoint can serve at any dtype."""
    from .serve.engine import InferenceEngine
    pairs = parse_config_string(cfg) if isinstance(cfg, str) else list(cfg)
    if dev:
        pairs = pairs + [("dev", dev)]
    return InferenceEngine.from_checkpoint(pairs, model_path,
                                           layout=layout, **kw)


def engine_predict(engine, data, raw: bool = False) -> np.ndarray:
    """Engine prediction on raw arrays (NCHW 4-D or flat 2-D, like
    Net.predict): argmax classes, or full top-node rows with raw=True."""
    return engine.predict_raw(data) if raw else engine.predict(data)


def train(cfg: Union[str, ConfigPairs], data, label=None, num_round: int = 1,
          param: Union[Dict, Sequence[Tuple[str, str]], None] = None,
          eval_data: Optional[DataIter] = None, print_step: int = 100,
          silent: bool = False) -> Net:
    """Convenience training loop (reference wrapper/cxxnet.py:288-314)."""
    net = Net(cfg=cfg)
    if param:
        items = param.items() if isinstance(param, dict) else param
        for k, v in items:
            net.set_param(k, v)
    net.init_model()
    if isinstance(data, DataIter):
        for r in range(num_round):
            net.start_round(r)
            data.before_first()
            scounter = 0
            while data.next():
                net.update(data)
                scounter += 1
                if scounter % print_step == 0 and not silent:
                    print(f"[{r}] {scounter} batch passed")
            line = net.trainer.train_metric_report("train") \
                if net.trainer.eval_train else ""
            if eval_data is not None:
                line += net.evaluate(eval_data, "eval")
            if not silent and line:
                print(f"round {r}{line}")
    else:
        # raw-array branch: honor the configured batch_size by minibatching
        # (improvement over the reference loop, which updates on the whole
        # array at once — wrapper/cxxnet.py:309-314); tail is padded+masked.
        arr = _to_nhwc(data, net._layout)
        lab = _to_label(label, arr.shape[0])
        bs = net._build().batch_size
        n = arr.shape[0]
        for r in range(num_round):
            net.start_round(r)
            for off in range(0, n, bs):
                d, l = arr[off:off + bs], lab[off:off + bs]
                padd = bs - d.shape[0]
                if padd:
                    d = np.concatenate([d, np.repeat(d[-1:], padd, 0)])
                    l = np.concatenate([l, np.repeat(l[-1:], padd, 0)])
                net.update(DataBatch(data=d, label=l, num_batch_padd=padd))
            line = net.trainer.train_metric_report("train") \
                if net.trainer.eval_train else ""
            if eval_data is not None:
                line += net.evaluate(eval_data, "eval")
            if not silent and line:
                print(f"round {r}{line}")
    return net
