#!/bin/sh
# Build the native data-plane library (JPEG decode + record scan).
# Mirrors the role of the reference's Makefile USE_OPENCV_DECODER=0 path
# (libjpeg fallback decoder, src/utils/decoder.h).
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -o libcxxnet_native.so decode.cc -ljpeg
echo "built $(pwd)/libcxxnet_native.so"
