#!/bin/sh
# Build the native data-plane library (JPEG decode + record scan).
# Mirrors the role of the reference's Makefile USE_OPENCV_DECODER=0 path
# (libjpeg fallback decoder, src/utils/decoder.h).
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -o libcxxnet_native.so decode.cc -ljpeg
echo "built $(pwd)/libcxxnet_native.so"

# C ABI (reference wrapper/cxxnet_wrapper.h analog): embeds CPython and
# delegates to cxxnet_tpu.capi_bridge. Optional: skipped (without failing
# the data-plane build above) when the CPython embed toolchain is missing.
if EMBED_FLAGS=$(python3-config --includes --ldflags --embed 2>/dev/null); then
  g++ -O3 -fPIC -shared -o libcxxnet_capi.so capi.cc ${EMBED_FLAGS}
  echo "built $(pwd)/libcxxnet_capi.so"
else
  echo "skipped libcxxnet_capi.so (no python3-config --embed support)"
fi
