// Native data-plane kernels: JPEG decode + record scanning.
//
// TPU-native replacement for the reference's native IO substrate: the
// libjpeg decoder (/root/reference/src/utils/decoder.h:21-115) and the
// OpenMP parallel decode loop in the imgrec parser
// (/root/reference/src/io/iter_image_recordio-inl.hpp:206-250). Exposed as
// a plain C ABI consumed via ctypes (cxxnet_tpu/io/native.py); every entry
// point is thread-safe so a Python thread pool gets true parallel decode
// (ctypes releases the GIL for the duration of the call).
//
// Build: cxxnet_tpu/native/build.sh  ->  libcxxnet_native.so

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

void silent_output(j_common_ptr) {}

}  // namespace

extern "C" {

// Query the dimensions of a JPEG. Returns 0 on success.
int cxn_jpeg_dims(const uint8_t* buf, long len, int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.output_message = silent_output;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  *c = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode a JPEG into `out` (row-major HWC, uint8), which must hold
// h*w*want_c bytes (dims from cxn_jpeg_dims). want_c: 3 = RGB, 1 = gray.
// Returns 0 on success.
int cxn_jpeg_decode(const uint8_t* buf, long len, int want_c, uint8_t* out,
                    int out_h, int out_w) {
  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.output_message = silent_output;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (want_c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (static_cast<int>(cinfo.output_height) != out_h ||
      static_cast<int>(cinfo.output_width) != out_w ||
      static_cast<int>(cinfo.output_components) != want_c) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  const int stride = out_w * want_c;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<long>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Scan a record buffer for framed records (magic 0xCED7ABEF, see
// cxxnet_tpu/io/recordio.py). Fills offsets[i], lengths[i] for up to
// max_records payloads starting inside buf[0..len). Returns record count.
int cxn_scan_records(const uint8_t* buf, long len, long* offsets,
                     long* lengths, int max_records) {
  const uint32_t kMagic = 0xCED7ABEFu;
  long pos = 0;
  int n = 0;
  while (pos + 8 <= len && n < max_records) {
    uint32_t magic, plen;
    std::memcpy(&magic, buf + pos, 4);
    if (magic != kMagic) {  // resync forward on 8-byte boundaries
      pos += 8;
      continue;
    }
    std::memcpy(&plen, buf + pos + 4, 4);
    if (pos + 8 + plen > static_cast<unsigned long>(len)) break;
    offsets[n] = pos + 8;
    lengths[n] = plen;
    ++n;
    long adv = 8 + plen;
    adv += (8 - adv % 8) % 8;
    pos += adv;
  }
  return n;
}

// Subtract mean + scale in one pass: out[i] = (in[i] - mean[i]) * scale.
// The hot inner loop of the augment stage (vectorized by the compiler).
void cxn_normalize(const uint8_t* in, const float* mean, float scale,
                   float* out, long n) {
  if (mean) {
    for (long i = 0; i < n; ++i) out[i] = (in[i] - mean[i]) * scale;
  } else {
    for (long i = 0; i < n; ++i) out[i] = in[i] * scale;
  }
}

}  // extern "C"
