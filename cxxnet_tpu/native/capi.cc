// C ABI for the TPU-native framework, mirroring the reference C API
// (/root/reference/wrapper/cxxnet_wrapper.h:36-232 — CXNIO* iterator
// handles and CXNNet* net handles) so foreign-language hosts (C, MATLAB
// MEX-style bindings, etc.) can drive training/inference.
//
// Implementation: embeds CPython and delegates to cxxnet_tpu.capi_bridge.
// Works both from a non-Python host process (initializes the interpreter)
// and when loaded inside an existing Python process via ctypes (reuses it;
// every entry point takes the GIL). Array traffic crosses as read-only
// memoryviews in, (bytes, shape) out; returned pointers stay valid until
// the next call on any handle, matching the reference's "caller must copy
// the result out before calling any other cxxnet function" contract.
//
// Build: cxxnet_tpu/native/build.sh  ->  libcxxnet_capi.so

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

PyObject* g_bridge = nullptr;          // cxxnet_tpu.capi_bridge module
std::vector<char> g_buf;               // scratch for returned arrays
std::string g_str;                     // scratch for returned strings

class Gil {
 public:
  Gil() {
    // First-use interpreter init must be raced-free when a non-Python host
    // calls into the ABI from several threads at startup.
    static std::once_flag once;
    std::call_once(once, [] {
      if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        // Drop the GIL acquired by initialization so PyGILState_Ensure
        // below (and in future calls from any thread) behaves uniformly.
        PyEval_SaveThread();
      }
    });
    st_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st_); }

 private:
  PyGILState_STATE st_;
};

PyObject* Bridge() {
  if (g_bridge == nullptr) {
    g_bridge = PyImport_ImportModule("cxxnet_tpu.capi_bridge");
    if (g_bridge == nullptr) PyErr_Print();
  }
  return g_bridge;
}

// Call bridge.<fn>(args...); returns new reference or nullptr (error
// printed to stderr, mirroring the reference's utils::Error abort-free
// wrapper behavior as closely as a C ABI allows).
PyObject* Call(const char* fn, PyObject* args) {
  if (args == nullptr) {
    // Py_BuildValue/PyTuple_Pack failed at the call site; report that
    // failure rather than invoking the bridge with zero arguments.
    if (PyErr_Occurred() != nullptr) PyErr_Print();
    else fprintf(stderr, "cxxnet capi: %s called with null args\n", fn);
    return nullptr;
  }
  PyObject* mod = Bridge();
  if (mod == nullptr) { Py_XDECREF(args); return nullptr; }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) { PyErr_Print(); Py_XDECREF(args); return nullptr; }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (out == nullptr) PyErr_Print();
  return out;
}

PyObject* Mv(const float* p, uint64_t n_floats) {
  return PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(p)),
      static_cast<Py_ssize_t>(n_floats * sizeof(float)), PyBUF_READ);
}

PyObject* ShapeTuple(const unsigned* s, int n) {
  PyObject* t = PyTuple_New(n);
  for (int i = 0; i < n; ++i)
    PyTuple_SET_ITEM(t, i, PyLong_FromUnsignedLong(s[i]));
  return t;
}

uint64_t Prod(const unsigned* s, int n) {
  uint64_t p = 1;
  for (int i = 0; i < n; ++i) p *= s[i];
  return p;
}

// Unpack a (bytes, shape[, ndim]) result into g_buf / oshape.
const float* UnpackArray(PyObject* res, unsigned* oshape, int max_dim,
                         unsigned* out_dim) {
  if (res == nullptr || res == Py_None) {
    Py_XDECREF(res);
    // deterministic outputs on the error path (callers may read the
    // shape/stride before checking the data pointer)
    for (int i = 0; i < max_dim; ++i) oshape[i] = 0;
    if (out_dim != nullptr) *out_dim = 0;
    return nullptr;
  }
  if (!PyTuple_Check(res) || PyTuple_Size(res) < 2) {
    // an unexpected bridge return must not segfault the embedding host
    fprintf(stderr, "cxxnet capi: bridge returned non-(bytes, shape) value\n");
    Py_DECREF(res);
    for (int i = 0; i < max_dim; ++i) oshape[i] = 0;
    if (out_dim != nullptr) *out_dim = 0;
    return nullptr;
  }
  PyObject* bytes = PyTuple_GetItem(res, 0);   // borrowed
  PyObject* shape = PyTuple_GetItem(res, 1);
  char* data; Py_ssize_t len;
  if (!PyTuple_Check(shape) ||
      PyBytes_AsStringAndSize(bytes, &data, &len) != 0) {
    if (PyErr_Occurred() != nullptr) PyErr_Print();
    else fprintf(stderr, "cxxnet capi: bridge returned non-tuple shape\n");
    Py_DECREF(res);
    for (int i = 0; i < max_dim; ++i) oshape[i] = 0;
    if (out_dim != nullptr) *out_dim = 0;
    return nullptr;
  }
  g_buf.assign(data, data + len);
  int nd = static_cast<int>(PyTuple_Size(shape));
  for (int i = 0; i < max_dim; ++i)
    oshape[i] = i < nd
        ? static_cast<unsigned>(PyLong_AsUnsignedLong(PyTuple_GetItem(shape, i)))
        : 1;
  if (out_dim != nullptr) *out_dim = static_cast<unsigned>(nd);
  Py_DECREF(res);
  return reinterpret_cast<const float*>(g_buf.data());
}

}  // namespace

extern "C" {

// ---- iterator handle -------------------------------------------------------

void* CXNIOCreateFromConfig(const char* cfg) {
  Gil g;
  return Call("io_create", Py_BuildValue("(s)", cfg));
}

int CXNIONext(void* handle) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_INCREF(o);
  PyObject* r = Call("io_next", PyTuple_Pack(1, o));
  Py_DECREF(o);
  if (r == nullptr) return 0;
  int v = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return v;
}

void CXNIOBeforeFirst(void* handle) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_INCREF(o);
  Py_XDECREF(Call("io_before_first", PyTuple_Pack(1, o)));
  Py_DECREF(o);
}

const float* CXNIOGetData(void* handle, unsigned oshape[4],
                          unsigned* ostride) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_INCREF(o);
  PyObject* r = Call("io_get_data", PyTuple_Pack(1, o));
  Py_DECREF(o);
  const float* p = UnpackArray(r, oshape, 4, nullptr);
  if (ostride != nullptr) *ostride = oshape[3];
  return p;
}

const float* CXNIOGetLabel(void* handle, unsigned oshape[2],
                           unsigned* ostride) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_INCREF(o);
  PyObject* r = Call("io_get_label", PyTuple_Pack(1, o));
  Py_DECREF(o);
  const float* p = UnpackArray(r, oshape, 2, nullptr);
  if (ostride != nullptr) *ostride = oshape[1];
  return p;
}

void CXNIOFree(void* handle) {
  Gil g;
  Py_XDECREF(static_cast<PyObject*>(handle));
}

// ---- net handle ------------------------------------------------------------

void* CXNNetCreate(const char* device, const char* cfg) {
  Gil g;
  return Call("net_create",
              Py_BuildValue("(ss)", device == nullptr ? "" : device, cfg));
}

void CXNNetFree(void* handle) {
  Gil g;
  Py_XDECREF(static_cast<PyObject*>(handle));
}

void CXNNetSetParam(void* handle, const char* name, const char* val) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_XDECREF(Call("net_set_param", Py_BuildValue("(Oss)", o, name, val)));
}

void CXNNetInitModel(void* handle) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_INCREF(o);
  Py_XDECREF(Call("net_init_model", PyTuple_Pack(1, o)));
  Py_DECREF(o);
}

void CXNNetSaveModel(void* handle, const char* fname) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_XDECREF(Call("net_save_model", Py_BuildValue("(Os)", o, fname)));
}

void CXNNetLoadModel(void* handle, const char* fname) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_XDECREF(Call("net_load_model", Py_BuildValue("(Os)", o, fname)));
}

void CXNNetStartRound(void* handle, int round) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  Py_XDECREF(Call("net_start_round", Py_BuildValue("(Oi)", o, round)));
}

void CXNNetUpdateIter(void* handle, void* data_handle) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* it = static_cast<PyObject*>(data_handle);
  Py_XDECREF(Call("net_update_iter", Py_BuildValue("(OO)", o, it)));
}

void CXNNetUpdateBatch(void* handle, float* p_data, const unsigned dshape[4],
                       float* p_label, const unsigned lshape[2]) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* args = Py_BuildValue(
      "(ONONO)", o, Mv(p_data, Prod(dshape, 4)), ShapeTuple(dshape, 4),
      Mv(p_label, Prod(lshape, 2)), ShapeTuple(lshape, 2));
  Py_XDECREF(Call("net_update_batch", args));
}

const float* CXNNetPredictBatch(void* handle, float* p_data,
                                const unsigned dshape[4],
                                unsigned* out_size) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* args = Py_BuildValue(
      "(ONO)", o, Mv(p_data, Prod(dshape, 4)), ShapeTuple(dshape, 4));
  PyObject* r = Call("net_predict_batch", args);
  if (r == nullptr) { *out_size = 0; return nullptr; }
  char* data; Py_ssize_t len;
  PyBytes_AsStringAndSize(PyTuple_GetItem(r, 0), &data, &len);
  g_buf.assign(data, data + len);
  *out_size = static_cast<unsigned>(
      PyLong_AsUnsignedLong(PyTuple_GetItem(r, 1)));
  Py_DECREF(r);
  return reinterpret_cast<const float*>(g_buf.data());
}

const float* CXNNetPredictIter(void* handle, void* data_handle,
                               unsigned* out_size) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* it = static_cast<PyObject*>(data_handle);
  PyObject* r = Call("net_predict_iter", Py_BuildValue("(OO)", o, it));
  if (r == nullptr) { *out_size = 0; return nullptr; }
  char* data; Py_ssize_t len;
  PyBytes_AsStringAndSize(PyTuple_GetItem(r, 0), &data, &len);
  g_buf.assign(data, data + len);
  *out_size = static_cast<unsigned>(
      PyLong_AsUnsignedLong(PyTuple_GetItem(r, 1)));
  Py_DECREF(r);
  return reinterpret_cast<const float*>(g_buf.data());
}

const float* CXNNetExtractBatch(void* handle, float* p_data,
                                const unsigned dshape[4],
                                const char* node_name, unsigned oshape[4]) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* args = Py_BuildValue(
      "(ONOs)", o, Mv(p_data, Prod(dshape, 4)), ShapeTuple(dshape, 4),
      node_name);
  return UnpackArray(Call("net_extract_batch", args), oshape, 4, nullptr);
}

const float* CXNNetExtractIter(void* handle, void* data_handle,
                               const char* node_name, unsigned oshape[4]) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* it = static_cast<PyObject*>(data_handle);
  PyObject* args = Py_BuildValue("(OOs)", o, it, node_name);
  return UnpackArray(Call("net_extract_iter", args), oshape, 4, nullptr);
}

const char* CXNNetEvaluate(void* handle, void* data_handle,
                           const char* data_name) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* it = static_cast<PyObject*>(data_handle);
  PyObject* r = Call("net_evaluate", Py_BuildValue("(OOs)", o, it, data_name));
  if (r == nullptr) return nullptr;
  const char* s = PyUnicode_AsUTF8(r);
  g_str = s == nullptr ? "" : s;
  Py_DECREF(r);
  return g_str.c_str();
}

const float* CXNNetGetWeight(void* handle, const char* layer_name,
                             const char* wtag, unsigned wshape[4],
                             unsigned* out_dim) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* r = Call("net_get_weight",
                     Py_BuildValue("(Oss)", o, layer_name, wtag));
  if (r == nullptr || r == Py_None) {
    Py_XDECREF(r);
    *out_dim = 0;
    return nullptr;
  }
  return UnpackArray(r, wshape, 4, out_dim);
}

void CXNNetSetWeight(void* handle, float* p_weight, unsigned size_weight,
                     const char* layer_name, const char* wtag) {
  Gil g;
  PyObject* o = static_cast<PyObject*>(handle);
  PyObject* args = Py_BuildValue(
      "(ONIss)", o, Mv(p_weight, size_weight), size_weight, layer_name, wtag);
  Py_XDECREF(Call("net_set_weight", args));
}

}  // extern "C"
