"""Online inference subsystem: the serving analog of the training stack.

The reference cxxnet ships inference only as offline task modes
(``task = pred / pred_raw / extract_feature``, cxxnet_main.cpp); this
subpackage applies the same ahead-of-time-compiled, device-resident
philosophy to request-driven prediction:

* :mod:`.engine`  — ``InferenceEngine``: frozen params + a bucketed LRU of
  AOT-compiled predict executables (fixed shape buckets, one compile per
  bucket, steady-state traffic never recompiles);
* :mod:`.batcher` — ``MicroBatcher``: dynamic micro-batching queue that
  amortizes per-call dispatch overhead into device batches, with
  backpressure, per-request deadlines, and an optional circuit breaker
  (``resilience.CircuitBreaker`` — fail-fast 503s when the device is
  wedged, half-open probe recovery);
* :mod:`.stats`   — ``ServingStats``: rolling QPS, latency percentiles,
  batch-fill ratio, compile-cache hit/miss accounting;
* :mod:`.server`  — stdlib ``http.server`` JSON front-end
  (``/predict``, ``/extract``, ``/healthz``, ``/statz``);
* :mod:`.fleet`   — ``ReplicaPool``: N engines over disjoint device
  slices behind an SLO-aware router (queue depth + breaker state +
  burn rate, admission control, A/B version pinning);
* :mod:`.reload`  — ``ReloadWatcher``: zero-downtime hot weight reload
  from the checkpoint directory (verified scan, rolling drain+swap,
  A/B canary subsets);
* :mod:`.cascade` — ``CascadeRouter``: confidence-routed two-tier
  serving (int8 tier answers high-confidence rows, the rest escalate
  to the flagship tier — doc/tasks.md "Quantized serving & cascade").
"""

from ..resilience import CircuitBreaker, CircuitOpen
from .engine import InferenceEngine, negotiate_blob
from .batcher import MicroBatcher, Backpressure, DeadlineExceeded
from .stats import ServingStats
from .fleet import (AllReplicasDegraded, NoHealthyReplica, Replica,
                    ReplicaPool, UnknownVersion)
from .cascade import CascadeRouter
from .reload import ReloadWatcher
from .server import ServeServer

__all__ = ["InferenceEngine", "MicroBatcher", "Backpressure",
           "DeadlineExceeded", "ServingStats", "ServeServer",
           "CircuitBreaker", "CircuitOpen", "ReplicaPool", "Replica",
           "ReloadWatcher", "NoHealthyReplica", "AllReplicasDegraded",
           "UnknownVersion", "CascadeRouter", "negotiate_blob"]
