"""Paged KV-cache block accounting: a host-side allocator over a
fixed pool of fixed-size token blocks (the vLLM PagedAttention layout
adapted to this codebase's bucketed-compile discipline).

The DEVICE side is a preallocated ``(num_blocks, block_size, heads,
head_dim)`` array per attention layer (serve/lm/engine.py owns those);
this module owns only the integer bookkeeping: which blocks are free,
which sequence holds which blocks, and the compaction permutation a
defrag applies. Block **0 is reserved scratch**: per-sequence block
tables are fixed-width ``(T,)`` arrays padded with 0, and the compiled
step function scatters every masked/padding token write into block 0 —
so the allocator never hands it out, and nothing ever reads it through
the attention mask.

Occupancy rides the process registry (``cxxnet_lm_kv_blocks_used`` /
``cxxnet_lm_kv_pool_blocks``, labeled by engine instance like every
``cxxnet_serve_*`` family) so a dashboard sees cache pressure next to
queue depth. Thread-safe; the scheduler thread is the only steady-state
caller but tests and the whole-request path allocate concurrently.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import numpy as np

from ...telemetry.registry import REGISTRY

__all__ = ["BlockPool", "PoolExhausted", "SCRATCH_BLOCK"]

#: block id every padded / masked write lands in; never allocated
SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """No free KV blocks — the caller decides the eviction policy
    (the scheduler evicts the most-recently-admitted sequence)."""


class BlockPool:
    """Free-list allocator over blocks ``1 .. num_blocks-1``."""

    def __init__(self, num_blocks: int, block_size: int,
                 instance: str = ""):
        if num_blocks < 2:
            raise ValueError(
                f"kv pool needs >= 2 blocks (block 0 is scratch), "
                f"got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"kv block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # LIFO free list: a freed block is reused first, which keeps the
        # hot working set of pool indices small between defrags
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owner: Dict[int, int] = {}      # block id -> sequence id
        self.instance = instance
        self._g_used_fam = REGISTRY.gauge(
            "cxxnet_lm_kv_blocks_used",
            "Allocated KV-cache blocks (block 0 scratch excluded)",
            labels=("engine",))
        self._g_cap_fam = REGISTRY.gauge(
            "cxxnet_lm_kv_pool_blocks",
            "Allocatable KV-cache pool blocks",
            labels=("engine",))
        self._g_used = self._g_used_fam.labels(instance)
        self._g_cap = self._g_cap_fam.labels(instance)
        self._g_used.set(0)
        self._g_cap.set(self.num_blocks - 1)

    # -- accounting ------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def used(self) -> int:
        with self._lock:
            return len(self._owner)

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache entries."""
        return -(-int(n_tokens) // self.block_size)

    # -- alloc / free ----------------------------------------------------
    def alloc(self, n: int, seq_id: int) -> List[int]:
        """Allocate ``n`` blocks for ``seq_id`` — all or nothing, so a
        partial grant can never strand blocks on a raise."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if len(self._free) < n:
                raise PoolExhausted(
                    f"kv pool exhausted: need {n} block(s), "
                    f"{len(self._free)}/{self.capacity} free")
            got = [self._free.pop() for _ in range(n)]
            for b in got:
                self._owner[b] = int(seq_id)
            self._g_used.set(len(self._owner))
            return got

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the pool. Double-free and scratch-free are
        loud errors — both mean the block-table bookkeeping corrupted,
        and a silently shared block serves one sequence another
        sequence's keys."""
        with self._lock:
            for b in blocks:
                b = int(b)
                if b == SCRATCH_BLOCK:
                    raise ValueError("cannot free the scratch block 0")
                if b not in self._owner:
                    raise ValueError(f"double free of kv block {b}")
                del self._owner[b]
                self._free.append(b)
            self._g_used.set(len(self._owner))

    def owners(self) -> Dict[int, int]:
        """{block id: sequence id} snapshot (tests / debugging)."""
        with self._lock:
            return dict(self._owner)

    # -- defrag ----------------------------------------------------------
    def defrag_plan(self) -> Tuple[np.ndarray, Dict[int, int]]:
        """Compaction plan: allocated blocks move to the contiguous
        front ``1..used`` (in ascending current-id order — stable, so a
        repeated defrag is the identity).

        Returns ``(old_of_new, remap)``: ``old_of_new`` is a
        permutation of ``0..num_blocks-1`` with ``old_of_new[new] =
        old`` — the gather index the engine applies to every pool array
        (``pool[old_of_new]``) — and ``remap`` maps each moved block's
        old id to its new id for table rewriting. The plan is applied
        atomically by the ENGINE (pool gather + table rewrite must
        happen under its lock while no step is in flight); this method
        also commits the allocator's own free list to the compacted
        layout, so call it only when the plan will be applied."""
        with self._lock:
            alive = sorted(self._owner)
            old_of_new = np.empty((self.num_blocks,), np.int32)
            old_of_new[0] = SCRATCH_BLOCK
            remap: Dict[int, int] = {}
            for new_id, old_id in enumerate(alive, start=1):
                old_of_new[new_id] = old_id
                remap[old_id] = new_id
            tail = [b for b in range(1, self.num_blocks) if b not in remap]
            for off, old_id in enumerate(tail):
                old_of_new[1 + len(alive) + off] = old_id
            self._owner = {remap[b]: sid for b, sid in self._owner.items()}
            self._free = list(range(self.num_blocks - 1, len(alive), -1))
            return old_of_new, remap

    def unregister(self) -> None:
        """Drop this pool's gauges from the registry (engine close)."""
        self._g_used_fam.remove_labels(self.instance)
        self._g_cap_fam.remove_labels(self.instance)
