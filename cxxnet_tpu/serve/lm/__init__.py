"""Autoregressive LM serving: paged KV-cache, continuous batching,
token streaming, prefill/decode disaggregation.

Layering (each module only reaches down):

* ``blocks``    — host-side block allocator + occupancy gauges
* ``engine``    — LMEngine: paged attention step cells over a wrapped
                  InferenceEngine (weights / mesh / hot-reload shared)
* ``stream``    — ndjson event + HTTP chunked framing helpers
* ``handoff``   — prefill->decode KV shipping (data_service wire)
* ``scheduler`` — continuous-batching loop, StreamHandle, roles

Entry points: ``ReplicaPool.attach_lm`` wires one LMScheduler per
replica; ``ServeServer`` exposes ``POST /generate`` (streaming).
"""

from .blocks import BlockPool, PoolExhausted, SCRATCH_BLOCK
from .engine import LMEngine
from .scheduler import LMScheduler, StreamHandle

__all__ = ["BlockPool", "PoolExhausted", "SCRATCH_BLOCK", "LMEngine",
           "LMScheduler", "StreamHandle"]
