"""LMEngine: autoregressive decoding over a paged KV-cache.

Wraps an existing :class:`serve.engine.InferenceEngine` (weights,
mesh, hot-reload machinery stay THEIRS — a fleet weight swap lands
here for free through the shared ``_weights_lock`` pair-read) and adds
the LM execution model the whole-request engine cannot express:

* **paged KV-cache** — one preallocated ``(num_blocks, block_size,
  heads, head_dim)`` pool per attention layer, sharded over the
  replica's device slice by ``parallel/rules.py`` partition specs
  (``kv_cache_rules`` — heads over the mesh 'model' axis, the SAME
  placement the mha q/k/v projections declare). Per-sequence block
  tables are fixed-width ``(T,)`` host arrays padded with the scratch
  block 0; attention over the cache is ``ops.attention.paged_attention``
  (gather by table, mask by length).

* **one traced step function** for prefill AND decode: prefill runs it
  at ``(B=1, C=prefill_chunk)``, decode at ``(B=max_seqs, C=1)`` — two
  compiled cells total, LRU'd with hit/miss counters like the
  whole-request engine's bucket cache, so steady-state decode performs
  ZERO recompiles. Every shape in the cell is static (fixed T, fixed
  C); varying sequence lengths live entirely in the ``lengths`` mask.

* **bit-parity by construction** — every op in the step is row-
  independent (einsums batch over rows, layernorm/softmax are
  per-position), block ids never enter the math (the table gather
  produces identical values wherever the blocks live), and both the
  continuous-batching scheduler and :meth:`generate_whole` drive the
  SAME compiled cells with identical per-row inputs — so greedy tokens
  are bit-identical between the two paths (asserted in
  tests/test_lm_serve.py).

The graph is interpreted layer-by-layer: embed / posembed / mha get
position-aware custom paths (``rope_at``, cache scatter, paged
attention); layernorm / ffn / seqfc / add reuse ``layer.apply``
verbatim — same weights, same math, same dtypes as training.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...config import LMServeConfig, parse_policy
from ...telemetry.registry import REGISTRY
from ..engine import InferenceEngine
from .blocks import SCRATCH_BLOCK, BlockPool

#: layer types the LM interpreter understands; anything else in the
#: graph is a loud build-time error, not a silent wrong answer
SUPPORTED_TYPES = frozenset({"embed", "layernorm", "posembed", "mha",
                             "ffn", "seqfc", "add", "lmloss"})


class LMEngine:
    """Paged-KV autoregressive engine over a wrapped InferenceEngine."""

    def __init__(self, engine: InferenceEngine, cfg: LMServeConfig):
        import jax.numpy as jnp
        self.engine = engine
        self.cfg = cfg
        tr = engine.trainer
        self.trainer = tr
        c, y, s = tr.graph.input_shape
        if c != 1 or y != 1:
            raise ValueError(
                "lm serve needs a flat (1,1,S) token-id input node, got "
                f"input_shape {tr.graph.input_shape}")
        self.block_size = cfg.kv_block_size
        self.num_blocks = cfg.kv_pool_blocks
        self.max_seqs = cfg.max_seqs
        self.max_context = cfg.max_context
        self.chunk = cfg.prefill_chunk
        #: fixed block-table width — EVERY compiled shape uses this T;
        #: a varying T would change the attention reduction bracketing
        #: and break bit-parity between paths
        self.T = cfg.max_blocks_per_seq
        self.compute_dtype = engine.compute_dtype
        self.kv_dtype = (parse_policy(cfg.kv_dtype).compute_dtype
                         if cfg.kv_dtype else self.compute_dtype)
        self.vocab = 0
        self._mha: List[Tuple[int, object]] = []   # (layer idx, layer)
        self._validate_graph()
        self.block_pool = BlockPool(self.num_blocks, self.block_size,
                                    instance=engine.stats.instance)
        # device pools: {mha name: {"k"/"v": (N, bs, H, D)}}, placed by
        # the SAME rule machinery that places training params
        self.pools = self._init_pools(jnp)
        self._pool_lock = threading.Lock()
        # compiled-cell LRU (prefill cell, decode cell, kv-install
        # cell): mirrors InferenceEngine._compiled, with its own
        # counter family so the zero-steady-state-recompile contract
        # is assertable per engine
        self._cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._cache_lock = threading.Lock()
        fam = REGISTRY.counter(
            "cxxnet_lm_compile_cache_events_total",
            "LM step compile-cache events", labels=("engine", "event"))
        self._cc_fam = fam
        self._c_hit = fam.labels(engine.stats.instance, "hit")
        self._c_miss = fam.labels(engine.stats.instance, "miss")

    # -- validation / pools ----------------------------------------------
    def _validate_graph(self) -> None:
        g = self.trainer.graph
        net = self.trainer.net
        seen_loss = False
        for li, (spec, layer) in enumerate(zip(g.layers, net.layers)):
            if spec.type not in SUPPORTED_TYPES:
                raise ValueError(
                    f"lm serve: unsupported layer type {spec.type!r} "
                    f"({spec.name!r}); supported: "
                    + ", ".join(sorted(SUPPORTED_TYPES)))
            if layer.is_loss:
                seen_loss = True
                continue
            if seen_loss:
                raise ValueError(
                    "lm serve: loss layers must come last in the graph")
            if spec.type == "embed":
                self.vocab = layer.vocab_size
            if spec.type == "mha":
                if not layer.causal:
                    raise ValueError(
                        f"lm serve: mha {spec.name!r} must be causal "
                        "(causal = 1) for autoregressive decoding")
                if spec.is_shared:
                    raise ValueError(
                        "lm serve: weight-tied (shared) mha layers are "
                        "not supported — each graph position needs its "
                        "own KV pool")
                self._mha.append((li, layer))
            if spec.type == "posembed":
                e, s, _ = net.node_shapes[spec.nindex_in[0]]
                if s < self.max_context:
                    raise ValueError(
                        f"lm serve: posembed table covers {s} positions "
                        f"< lm_serve_max_context {self.max_context}")
        if not self._mha:
            raise ValueError("lm serve: graph has no mha layer")
        if self.vocab <= 0:
            raise ValueError("lm serve: graph has no embed layer")
        c, y, s = g.input_shape
        if s < self.chunk:
            # the prefill cell runs the graph at S = chunk; a posembed
            # sized to the training S would be the only S-sensitive
            # piece and is validated above — nothing else reads S
            pass

    def _init_pools(self, jnp):
        from jax.sharding import PartitionSpec as P
        from ...parallel.rules import kv_cache_rules, match_partition_rules
        net = self.trainer.net
        mesh = self.trainer.mesh
        shapes = {}
        for li, layer in self._mha:
            e = net.node_shapes[net.graph.layers[li].nindex_in[0]][0]
            h, d = layer.nhead, e // layer.nhead
            shape = (self.num_blocks, self.block_size, h, d)
            shapes[net.graph.layers[li].name] = {
                "k": np.zeros(shape, self.kv_dtype),
                "v": np.zeros(shape, self.kv_dtype)}
        specs = match_partition_rules(kv_cache_rules(), shapes)
        if mesh.model_parallel <= 1:
            specs = {n: {"k": P(), "v": P()} for n in shapes}
        return mesh.shard_params(shapes, specs)

    # -- the traced step -------------------------------------------------
    def _kv_write(self, pool, kv, tables, positions, lengths, jnp):
        """Scatter this step's keys/values into the pool. ``positions``
        at/after a row's ``lengths`` (chunk padding, dead rows) are
        redirected into the scratch block 0, which the attention mask
        never reads — so one fixed-shape scatter covers every case."""
        B, C = positions.shape
        valid = (positions >= 0) & (positions < lengths[:, None])
        blk = jnp.clip(positions // self.block_size, 0, self.T - 1)
        blocks = jnp.take_along_axis(tables, blk, axis=1)
        blocks = jnp.where(valid, blocks, SCRATCH_BLOCK)
        slots = jnp.where(valid, positions % self.block_size, 0)
        return pool.at[blocks, slots].set(kv.astype(pool.dtype))

    def _mha_step(self, layer, lparams, x, k_pool, v_pool, tables,
                  positions, lengths, cdt, jnp):
        """The mha layer's decode-path apply: identical projection /
        rope / output math to layers/seq.py, with attention over the
        paged cache instead of the in-activation k/v."""
        from ...ops.attention import paged_attention, rope_at
        B, C = positions.shape
        xs = x.reshape(B, C, x.shape[-1]).astype(cdt)

        def proj(nm):
            w = lparams[nm]["wmat"].astype(cdt)
            out = jnp.einsum("bse,ehd->bshd", xs, w)
            if "bias" in lparams[nm]:
                out = out + lparams[nm]["bias"].astype(cdt)
            return out

        q, k, v = proj("q"), proj("k"), proj("v")
        if layer.rope:
            pos = jnp.maximum(positions, 0)
            q = rope_at(q, layer.rope_theta, pos)
            k = rope_at(k, layer.rope_theta, pos)
        k_pool = self._kv_write(k_pool, k, tables, positions, lengths, jnp)
        v_pool = self._kv_write(v_pool, v, tables, positions, lengths, jnp)
        o = paged_attention(q.astype(k_pool.dtype), k_pool, v_pool,
                            tables, positions, lengths)
        wo = lparams["o"]["wmat"].astype(cdt)
        y = jnp.einsum("bshd,hde->bse", o.astype(cdt), wo)
        if "bias" in lparams["o"]:
            y = y + lparams["o"]["bias"].astype(cdt)
        return y.reshape(B, C, 1, y.shape[-1]), k_pool, v_pool

    def _make_step(self):
        """Build the (un-jitted) step function. ONE definition serves
        prefill and decode; the jit cache keys it by (B, C)."""
        import jax
        import jax.numpy as jnp
        from ...layers import ApplyCtx
        net = self.trainer.net
        g = net.graph
        cdt = self.compute_dtype
        mha_at = {li for li, _ in self._mha}
        out_node = None
        for spec, layer in zip(g.layers, net.layers):
            if not layer.is_loss:
                out_node = spec.nindex_out[0]

        def step(params, state, pools, ids, positions, tables, lengths,
                 last_idx):
            B, C = ids.shape
            nodes: List = [None] * g.num_nodes
            new_pools = dict(pools)
            for li, (spec, layer) in enumerate(zip(g.layers, net.layers)):
                if layer.is_loss:
                    continue
                if spec.type == "embed":
                    w = params[layer.name]["wmat"].astype(cdt)
                    out = jnp.take(w, jnp.maximum(ids, 0), axis=0)
                    nodes[spec.nindex_out[0]] = out.reshape(B, C, 1, -1)
                elif spec.type == "posembed":
                    pe = params[layer.name]["wmat"].astype(cdt)
                    p = jnp.clip(positions, 0, pe.shape[0] - 1)
                    add = jnp.take(pe, p, axis=0)
                    nodes[spec.nindex_out[0]] = (
                        nodes[spec.nindex_in[0]]
                        + add.reshape(B, C, 1, -1))
                elif li in mha_at:
                    name = spec.name
                    y, nk, nv = self._mha_step(
                        layer, params[name],
                        nodes[spec.nindex_in[0]],
                        new_pools[name]["k"], new_pools[name]["v"],
                        tables, positions, lengths, cdt, jnp)
                    new_pools[name] = {"k": nk, "v": nv}
                    nodes[spec.nindex_out[0]] = y
                else:
                    ctx = ApplyCtx(train=False,
                                   rng=jax.random.PRNGKey(0),
                                   compute_dtype=cdt)
                    inputs = [nodes[ni] for ni in spec.nindex_in]
                    outs, _ = layer.apply(params.get(layer.name, {}),
                                          state.get(layer.name, {}),
                                          inputs, ctx)
                    for ni, o in zip(spec.nindex_out, outs):
                        nodes[ni] = o
            logits = nodes[out_node].reshape(B, C, -1).astype(jnp.float32)
            last = logits[jnp.arange(B), last_idx]          # (B, V)
            token = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return token, last, new_pools

        return step

    def _compiled(self, key):
        """LRU lookup of a compiled cell (('step', B, C) or
        ('install',)); a miss builds + counts — the smoke asserts the
        miss counter FREEZES after warmup (zero steady-state
        recompiles)."""
        with self._cache_lock:
            fn = self._cache.get(key)
            if fn is not None:
                self._cache.move_to_end(key)
                self._c_hit.inc()
                return fn
            import jax
            if key[0] == "step":
                fn = jax.jit(self._make_step())
            else:
                fn = jax.jit(self._make_install())
            self._cache[key] = fn
            self._c_miss.inc()
            return fn

    def compile_info(self) -> Dict[str, int]:
        with self._cache_lock:
            return {"size": len(self._cache),
                    "hits": int(self._c_hit.value),
                    "misses": int(self._c_miss.value)}

    def _weights(self):
        """(params, net_state) pair-read under the wrapped engine's
        weights lock — a hot reload can never interleave."""
        tr = self.trainer
        with self.engine._weights_lock:
            return tr.params, tr.net_state

    # -- public step API (scheduler + whole-request path) ----------------
    def run_prefill(self, table: np.ndarray, ids: np.ndarray, p0: int,
                    n_real: int) -> int:
        """One prefill chunk for ONE sequence: write KV for tokens at
        positions ``p0 .. p0+n_real-1``, return the greedy token after
        the chunk's last real position (meaningful only for the
        prompt's final chunk). ``ids`` is the fixed-width chunk (C,)
        with padding beyond ``n_real``."""
        C = int(ids.shape[0])
        fn = self._compiled(("step", 1, C))
        positions = (p0 + np.arange(C, dtype=np.int32))[None, :]
        lengths = np.asarray([p0 + n_real], np.int32)
        last_idx = np.asarray([n_real - 1], np.int32)
        params, state = self._weights()
        with self._pool_lock:
            token, _last, new_pools = fn(
                params, state, self.pools, ids[None, :].astype(np.int32),
                positions, table[None, :].astype(np.int32), lengths,
                last_idx)
            self.pools = new_pools
            return int(np.asarray(token)[0])

    def run_decode(self, ids: np.ndarray, positions: np.ndarray,
                   tables: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """One continuous-batching decode step over the fixed
        ``max_seqs`` rows (C = 1). Dead rows carry ``lengths = 0`` and
        all-scratch tables; their outputs are garbage by contract and
        the scheduler never reads them. Returns greedy tokens (B,)."""
        B = self.max_seqs
        fn = self._compiled(("step", B, 1))
        params, state = self._weights()
        with self._pool_lock:
            token, _last, new_pools = fn(
                params, state, self.pools,
                ids.reshape(B, 1).astype(np.int32),
                positions.reshape(B, 1).astype(np.int32),
                tables.astype(np.int32), lengths.astype(np.int32),
                np.zeros((B,), np.int32))
            self.pools = new_pools
            return np.asarray(token)

    # -- whole-request reference path ------------------------------------
    def generate_whole(self, prompt, max_new: Optional[int] = None
                       ) -> List[int]:
        """Request-level greedy decode through the SAME compiled cells
        the continuous scheduler uses (prefill chunks, then the B-row
        decode cell with only row 0 live) — the bit-parity reference
        the digest test compares against, and a synchronous generate
        for tools. Allocates from the shared block pool and frees on
        exit."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(max_new or self.cfg.max_new_tokens)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + 1 > self.max_context:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate within lm_serve_max_context {self.max_context}")
        pool = self.block_pool
        table = np.zeros((self.T,), np.int32)
        blocks: List[int] = []

        def ensure(n_tokens):
            need = pool.blocks_for_tokens(n_tokens)
            while len(blocks) < need:
                got = pool.alloc(1, seq_id=-1)
                table[len(blocks)] = got[0]
                blocks.extend(got)

        try:
            token = None
            p0 = 0
            while p0 < prompt.size:
                c = min(self.chunk, prompt.size - p0)
                ids = np.zeros((self.chunk,), np.int32)
                ids[:c] = prompt[p0:p0 + c]
                ensure(p0 + c)
                token = self.run_prefill(table, ids, p0, c)
                p0 += c
            generated = [token]
            L = prompt.size
            eos = self.cfg.eos
            while (len(generated) < max_new and L < self.max_context
                   and not (eos >= 0 and generated[-1] == eos)):
                ensure(L + 1)
                B = self.max_seqs
                ids = np.zeros((B,), np.int32)
                positions = np.zeros((B,), np.int32)
                tables = np.zeros((B, self.T), np.int32)
                lengths = np.zeros((B,), np.int32)
                ids[0] = generated[-1]
                positions[0] = L
                tables[0] = table
                lengths[0] = L + 1
                toks = self.run_decode(ids, positions, tables, lengths)
                generated.append(int(toks[0]))
                L += 1
            return generated
        finally:
            if blocks:
                pool.free(blocks)

    # -- KV extraction / injection (prefill/decode disaggregation) -------
    def extract_kv(self, table: np.ndarray) -> Dict[str, Dict[str, np.ndarray]]:
        """Host copy of one sequence's cache blocks, gathered by its
        table — full fixed ``(T, bs, H, D)`` shape (padding blocks are
        scratch content the receiving mask never reads), so the
        install cell compiles exactly once."""
        idx = np.asarray(table, np.int32)
        with self._pool_lock:
            return {name: {kv: np.asarray(p[kv][idx])
                           for kv in ("k", "v")}
                    for name, p in self.pools.items()}

    def _make_install(self):
        def install(pools, table, kv):
            out = dict(pools)
            for name, ent in kv.items():
                out[name] = {
                    "k": pools[name]["k"].at[table].set(
                        ent["k"].astype(pools[name]["k"].dtype)),
                    "v": pools[name]["v"].at[table].set(
                        ent["v"].astype(pools[name]["v"].dtype))}
            return out
        return install

    def install_kv(self, table: np.ndarray,
                   kv: Dict[str, Dict[str, np.ndarray]]) -> None:
        """Scatter a shipped sequence's KV state into this engine's
        pools at the receiver's own block table (one compiled cell,
        fixed shape — handoffs don't recompile either)."""
        if set(kv) != set(self.pools):
            raise ValueError(
                f"kv handoff layers {sorted(kv)} != engine layers "
                f"{sorted(self.pools)}")
        fn = self._compiled(("install",))
        with self._pool_lock:
            self.pools = fn(self.pools, np.asarray(table, np.int32), kv)

    # -- defrag ----------------------------------------------------------
    def defrag(self) -> Dict[int, int]:
        """Compact allocated blocks to the front of the pool: gather
        every pool array through the allocator's permutation and return
        the old->new id remap the caller applies to its block tables.
        Runs under the pool lock — no step is in flight while blocks
        move, so the gather + table rewrite is atomic."""
        import jax.numpy as jnp
        with self._pool_lock:
            old_of_new, remap = self.block_pool.defrag_plan()
            perm = jnp.asarray(old_of_new)
            self.pools = {name: {"k": p["k"][perm], "v": p["v"][perm]}
                          for name, p in self.pools.items()}
            return remap

    def close(self) -> None:
        self.block_pool.unregister()
        self._cc_fam.remove_labels(self.engine.stats.instance, "hit")
        self._cc_fam.remove_labels(self.engine.stats.instance, "miss")
