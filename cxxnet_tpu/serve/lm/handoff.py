"""Prefill -> decode KV handoff over the data_service wire protocol.

One TCP connection per handed-off sequence, framed exactly like the
data-service shards (``data_service/wire.py``: magic + length-prefixed
msgpack-free header JSON + raw little-endian arrays) so the two wire
formats share tooling and failure modes:

  client (prefill replica)                server (decode replica)
  ------------------------                -----------------------
  frame {type: prefill_handoff,    -->    recv_frame
         prompt_len, first_token,         scheduler.admit_handoff(...)
         max_new, deadline_ms}
         arrays: <layer>/k, <layer>/v
                                   <--    frame {type: event, data: {...}}
                                          ... one per stream event ...
                                   <--    terminal done/error event
  relay each event into the local StreamHandle; close.

The KV arrays ship at the FULL fixed table shape ``(T, block_size,
heads, head_dim)`` per layer — padding rows are scratch content the
receiving attention mask never reads — so the decode side's install
cell has one static shape and handoffs never recompile anything.

Every replica runs a listener (ephemeral port by default) regardless of
role, so flipping a fleet to a prefill/decode split mid-run is a pair
of ``set_role`` calls, not a restart.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ...data_service.wire import WireError, pack_frame, recv_frame
from ..batcher import Backpressure, DeadlineExceeded
from .blocks import PoolExhausted

__all__ = ["HandoffListener", "ship_prefill"]

#: relay read cap when the request carries no deadline of its own
_RELAY_TIMEOUT_S = 60.0


def _flatten_kv(kv: Dict[str, Dict[str, np.ndarray]]):
    arrays = []
    for name in sorted(kv):
        arrays.append((f"{name}/k", np.ascontiguousarray(kv[name]["k"])))
        arrays.append((f"{name}/v", np.ascontiguousarray(kv[name]["v"])))
    return arrays


def _unflatten_kv(arrays: Dict[str, np.ndarray]
                  ) -> Dict[str, Dict[str, np.ndarray]]:
    kv: Dict[str, Dict[str, np.ndarray]] = {}
    for key, arr in arrays.items():
        name, _, which = key.rpartition("/")
        if which not in ("k", "v") or not name:
            raise WireError(f"bad kv array name {key!r}")
        kv.setdefault(name, {})[which] = arr
    for name, ent in kv.items():
        if set(ent) != {"k", "v"}:
            raise WireError(f"kv layer {name!r} missing k or v")
    return kv


def ship_prefill(peer: Tuple[str, int], prompt_len: int, first_token: int,
                 max_new: int, deadline_ms: float,
                 kv: Dict[str, Dict[str, np.ndarray]], handle) -> None:
    """Send one prefilled sequence to ``peer`` and relay the decode
    replica's event stream into ``handle`` until the terminal event.
    Never raises — wire failures become an error event on the handle
    (the local blocks are already freed by the caller)."""
    header = {"type": "prefill_handoff", "prompt_len": int(prompt_len),
              "first_token": int(first_token), "max_new": int(max_new),
              "deadline_ms": float(deadline_ms)}
    deadline = time.monotonic() + (deadline_ms / 1e3 if deadline_ms
                                   else _RELAY_TIMEOUT_S)
    try:
        with socket.create_connection(peer, timeout=5.0) as sock:
            sock.sendall(pack_frame(header, _flatten_kv(kv)))
            while True:
                hdr, _ = recv_frame(sock, deadline=deadline)
                ev = hdr.get("data", {})
                if handle.cancelled and ev.get("event") == "token":
                    # client went away mid-relay: surface locally; the
                    # remote side finishes on its own budget
                    continue
                handle.push(ev)
                if ev.get("event") in ("done", "error"):
                    return
    except (WireError, OSError) as exc:
        handle.push({"event": "error", "reason": "handoff",
                     "error": f"prefill handoff to {peer[0]}:{peer[1]} "
                              f"failed: {exc}"})


class HandoffListener:
    """Per-replica TCP listener admitting handed-off sequences into the
    local scheduler and streaming their events back."""

    def __init__(self, scheduler, port: int = 0, host: str = "127.0.0.1"):
        self.scheduler = scheduler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.addr: Tuple[str, int] = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="lm-handoff-listener",
            daemon=True)
        self._conns: list = []

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._conns:
            t.join(timeout=5.0)
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                      # socket closed: shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="lm-handoff-conn")
            # prune finished handlers: a long-lived replica must not
            # hold one dead thread object per handoff it ever served
            self._conns = [c for c in self._conns if c.is_alive()]
            self._conns.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        handle = None
        try:
            with conn:
                hdr, arrays = recv_frame(
                    conn, deadline=time.monotonic() + _RELAY_TIMEOUT_S)
                if hdr.get("type") != "prefill_handoff":
                    raise WireError(
                        f"unexpected handoff frame type {hdr.get('type')!r}")
                try:
                    handle = self.scheduler.admit_handoff(
                        hdr["prompt_len"], hdr["first_token"],
                        hdr["max_new"], hdr.get("deadline_ms", 0.0),
                        _unflatten_kv(arrays))
                except (Backpressure, PoolExhausted) as exc:
                    self._send_event(conn, {
                        "event": "error", "reason": "pressure",
                        "error": str(exc)})
                    return
                except (ValueError, DeadlineExceeded) as exc:
                    self._send_event(conn, {
                        "event": "error", "reason": "rejected",
                        "error": str(exc)})
                    return
                # relay budget follows the request's own deadline (the
                # scheduler evicts first and terminates the stream);
                # the flat cap only backstops deadline-less requests
                relay_s = _RELAY_TIMEOUT_S
                dl_ms = float(hdr.get("deadline_ms") or 0.0)
                if dl_ms:
                    relay_s = dl_ms / 1e3 + 5.0
                for ev in handle.events(timeout=relay_s):
                    self._send_event(conn, ev)
        except (WireError, OSError, TimeoutError):
            # peer gone or stream wedged: nothing to tell the peer, but
            # the local sequence must not keep a decode row + KV blocks
            # generating into a dead connection
            if handle is not None:
                handle.cancel()

    @staticmethod
    def _send_event(conn: socket.socket, ev: Dict) -> None:
        conn.sendall(pack_frame({"type": "event", "data": ev}))
