"""Token streaming wire format: ndjson events inside HTTP/1.1 chunks.

A ``/generate`` response is ``Transfer-Encoding: chunked`` where each
chunk carries exactly one newline-terminated JSON event, flushed as the
token is produced — so a client observes time-to-first-token and
inter-token latency directly, and the loadgen's percentile accounting
needs no protocol beyond "read chunks, split lines".

Events (one object per line):

* ``{"event": "token", "index": i, "token": t}`` — the i-th generated
  token (0-based; index 0's arrival IS the TTFT mark)
* ``{"event": "done", "reason": "eos"|"length"|"cancelled", "tokens":
  [...], "version": "r0007", "seq": 12}`` — terminal; full token list
  so non-streaming clients can ignore the increments
* ``{"event": "error", "error": "...", "reason": "deadline"|...}`` —
  terminal failure after streaming began (the HTTP status is already
  200 by then; this is the only way to signal it)

The chunk framing helpers live here rather than in server.py so the
framing unit test (tests/test_lm_serve.py) can round-trip frames
without a socket.
"""

from __future__ import annotations

import json
from typing import Dict, Iterator, List, Tuple

__all__ = ["encode_event", "decode_event", "chunk", "LAST_CHUNK",
           "iter_chunks", "split_events"]

#: terminating zero-length chunk per RFC 7230 §4.1
LAST_CHUNK = b"0\r\n\r\n"


def encode_event(event: Dict) -> bytes:
    """One ndjson line (the chunk payload) for a stream event."""
    return (json.dumps(event, separators=(",", ":")) + "\n").encode()


def decode_event(line: bytes) -> Dict:
    return json.loads(line.decode())


def chunk(payload: bytes) -> bytes:
    """Wrap a payload in HTTP/1.1 chunked framing (hex size line,
    CRLF, data, CRLF)."""
    return b"%X\r\n%s\r\n" % (len(payload), payload)


def iter_chunks(data: bytes) -> Iterator[bytes]:
    """Parse a chunked-encoded byte string back into payloads,
    stopping at (and validating) the terminal zero chunk. Raises
    ValueError on malformed framing — the framing test's oracle."""
    off = 0
    while True:
        eol = data.find(b"\r\n", off)
        if eol < 0:
            raise ValueError("chunked stream truncated in size line")
        size = int(data[off:eol], 16)
        off = eol + 2
        if size == 0:
            if data[off:off + 2] != b"\r\n":
                raise ValueError("missing final CRLF after last chunk")
            return
        payload = data[off:off + size]
        if len(payload) != size:
            raise ValueError("chunked stream truncated in payload")
        if data[off + size:off + size + 2] != b"\r\n":
            raise ValueError("missing CRLF after chunk payload")
        yield payload
        off += size + 2


def split_events(data: bytes) -> List[Dict]:
    """Decode a full chunked response body into its event list."""
    return [decode_event(p) for p in iter_chunks(data) if p.strip()]
