"""Continuous (iteration-level) batching scheduler for LM decoding.

One scheduler thread per LMEngine drives the Orca-style loop:

* every iteration runs AT MOST one prefill chunk (for the oldest
  sequence still prefilling) and then ONE decode step over the fixed
  ``max_seqs`` decode rows — so long prompts are chunked between decode
  steps and never stall in-flight generations;
* sequences are admitted into decode rows the moment a row and the KV
  blocks are free, and evicted the moment they finish — no batch
  barrier, no waiting for stragglers;
* eviction frees exactly the sequence's blocks: finish (eos / length),
  client cancel, deadline expiry, and pool-pressure eviction (the
  most-recently-admitted block-holder loses, preserving FIFO progress
  so the loop always drains — no starvation).

Results stream through :class:`StreamHandle`: the caller (server.py's
``/generate``, the handoff listener, tools) iterates ndjson-able event
dicts as tokens land. ``handle.result()`` is the synchronous view and
maps terminal errors onto the SAME exceptions the request batcher uses
(``Backpressure`` -> 503, ``DeadlineExceeded`` -> 504), so server.py's
error table needs no new rows.

Prefill/decode disaggregation: with ``role = "prefill"`` and a peer
address, a sequence that finishes prefill has its KV state extracted,
its LOCAL blocks freed, and the cache shipped to the peer's handoff
listener over the data_service wire protocol (see handoff.py); the
decode replica admits it via :meth:`admit_handoff` and events are
relayed back over the same connection. ``role = "decode"`` accepts
ONLY handoffs. Every replica runs the listener (ephemeral port,
``handoff_addr``) so a mid-run role split needs no restart.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...config import LMServeConfig
from ...telemetry.ledger import LEDGER
from ..batcher import Backpressure, DeadlineExceeded
from .blocks import PoolExhausted
from .engine import LMEngine

__all__ = ["LMScheduler", "StreamHandle", "Sequence"]


class StreamHandle:
    """Per-request event stream + synchronous result view."""

    def __init__(self, seq_id: int):
        self.seq_id = seq_id
        self._q: "queue.Queue[Dict]" = queue.Queue()
        self._done = threading.Event()
        self._cancel_cb = None
        self.cancelled = False

    # scheduler side --------------------------------------------------
    def push(self, event: Dict) -> None:
        self._q.put(event)
        if event.get("event") in ("done", "error"):
            self._done.set()

    # client side -----------------------------------------------------
    def cancel(self) -> None:
        """Client went away / asked to stop: the scheduler evicts the
        sequence at the next step and frees its blocks."""
        self.cancelled = True
        cb = self._cancel_cb
        if cb is not None:
            cb()

    def events(self, timeout: Optional[float] = None):
        """Yield events until the terminal one (inclusive)."""
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                raise TimeoutError("stream read timed out")
            ev = self._q.get(timeout=left)
            yield ev
            if ev.get("event") in ("done", "error"):
                return

    def result(self, timeout: Optional[float] = None) -> Dict:
        """Drain the stream; return the terminal 'done' event. Error
        events re-raise as the batcher exception of the same meaning."""
        last = None
        for ev in self.events(timeout=timeout):
            last = ev
        if last.get("event") == "error":
            reason = last.get("reason", "")
            if reason == "deadline":
                raise DeadlineExceeded(last.get("error", "lm deadline"))
            if reason == "pressure":
                raise Backpressure(last.get("error", "kv pool pressure"))
            raise RuntimeError(last.get("error", "lm generate failed"))
        return last


class Sequence:
    """Scheduler-internal per-request state."""

    __slots__ = ("seq_id", "prompt", "max_new", "deadline", "handle",
                 "table", "blocks", "p0", "generated", "admitted_at",
                 "row", "remote_src", "finished")

    def __init__(self, seq_id: int, prompt: np.ndarray, max_new: int,
                 deadline: Optional[float], handle: StreamHandle, T: int):
        self.seq_id = seq_id
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline          # absolute time.monotonic()
        self.handle = handle
        self.table = np.zeros((T,), np.int32)
        self.blocks: List[int] = []
        self.p0 = 0                       # prefill progress (tokens cached)
        self.generated: List[int] = []
        self.admitted_at = time.monotonic()
        self.row: Optional[int] = None
        self.remote_src = False           # admitted via handoff
        self.finished = False             # terminal; _finish ran


class LMScheduler:
    """Decode-step scheduler: continuous batching + streaming +
    prefill/decode disaggregation over one LMEngine."""

    def __init__(self, lm_engine: LMEngine, cfg: LMServeConfig,
                 role: Optional[str] = None,
                 peer: Optional[Tuple[str, int]] = None):
        self.engine = lm_engine
        self.cfg = cfg
        self.role = role or cfg.role
        self.peer = peer
        self._lock = threading.Lock()
        self._waiting: "deque[Sequence]" = deque()
        self._prefilling: "deque[Sequence]" = deque()
        self._ready: "deque[Sequence]" = deque()
        self._active: Dict[int, Sequence] = {}     # row -> seq
        self._free_rows: List[int] = list(range(cfg.max_seqs - 1, -1, -1))
        self._seq_counter = 0
        self._live = 0                             # admitted, not terminal
        self._wake = threading.Event()
        self._stopping = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="lm-scheduler", daemon=True)
        self._ship_threads: List[threading.Thread] = []
        self.listener = None
        self.handoff_addr: Optional[Tuple[str, int]] = None
        self.steps = 0
        self.evictions = 0

    # -- lifecycle -----------------------------------------------------
    def start(self, handoff_port: Optional[int] = None) -> None:
        from .handoff import HandoffListener
        self.listener = HandoffListener(
            self, port=self.cfg.handoff_port
            if handoff_port is None else handoff_port)
        self.listener.start()
        self.handoff_addr = self.listener.addr
        LEDGER.event("lm_serve_start", role=self.role,
                     max_seqs=self.cfg.max_seqs,
                     kv_blocks=self.engine.block_pool.capacity,
                     kv_block_size=self.cfg.kv_block_size,
                     handoff_port=self.handoff_addr[1])
        self._thread.start()

    def set_role(self, role: str,
                 peer: Optional[Tuple[str, int]] = None) -> None:
        """Flip this replica's plane mid-run (no restart): already-
        admitted sequences finish under the old plan; new prefill
        completions follow the new role."""
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"bad lm role {role!r}")
        with self._lock:
            self.role = role
            self.peer = peer
        self._wake.set()

    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        if drain:
            deadline = time.monotonic() + timeout
            while self.live_count() and time.monotonic() < deadline:
                time.sleep(0.01)
        # whatever is left gets cancelled so handles always terminate
        with self._lock:
            leftovers = (list(self._waiting) + list(self._prefilling)
                         + list(self._ready) + list(self._active.values()))
        for seq in leftovers:
            seq.handle.cancelled = True
        self._stopping.set()
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        # the loop may have exited before seeing the cancel flags —
        # sweep once more so every outstanding handle terminates and
        # every block goes back to the pool (safe even if the join
        # timed out and the loop is still running: _finish is
        # idempotent, so a racing double-finish is a no-op)
        self._sweep_expired()
        if self.listener is not None:
            self.listener.stop()
        for t in self._ship_threads:
            t.join(timeout=timeout)

    # -- admission -----------------------------------------------------
    def submit(self, prompt, max_new: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> StreamHandle:
        """Admit a prompt; returns immediately with the stream handle.
        Raises Backpressure (503) when the LM queue budget is spent."""
        with self._lock:
            if self.role == "decode":
                raise ValueError(
                    "decode-role replica accepts only prefill handoffs")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + 1 > self.cfg.max_context:
            raise ValueError(
                f"prompt length {prompt.size} exceeds lm_serve_max_context"
                f" {self.cfg.max_context} - 1")
        max_new = int(max_new or self.cfg.max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        dl_ms = self.cfg.deadline_ms if deadline_ms is None else deadline_ms
        deadline = time.monotonic() + dl_ms / 1e3 if dl_ms else None
        with self._lock:
            if len(self._waiting) + len(self._prefilling) \
                    >= self.cfg.max_queue:
                raise Backpressure(
                    f"lm queue full ({self.cfg.max_queue} sequences "
                    "waiting); retry later")
            self._seq_counter += 1
            seq = Sequence(self._seq_counter, prompt, max_new, deadline,
                           StreamHandle(self._seq_counter), self.engine.T)
            seq.handle._cancel_cb = self._wake.set
            self._waiting.append(seq)
            self._live += 1
        self._wake.set()
        return seq.handle

    def admit_handoff(self, prompt_len: int, first_token: int,
                      max_new: int, deadline_ms: float,
                      kv: Dict[str, Dict[str, np.ndarray]]) -> StreamHandle:
        """Decode-plane entry: install shipped KV state, emit the first
        token (computed by the prefill plane), and queue the sequence
        for decode rows. Runs on the handoff listener's connection
        thread; raises Backpressure / PoolExhausted back to the wire
        when this replica cannot take the sequence."""
        prompt_len = int(prompt_len)
        if prompt_len < 1 or prompt_len + 1 > self.cfg.max_context:
            raise ValueError(f"bad handoff prompt_len {prompt_len}")
        with self._lock:
            if len(self._ready) >= self.cfg.max_queue:
                raise Backpressure("lm decode queue full; retry later")
            self._seq_counter += 1
            seq_id = self._seq_counter
            self._live += 1
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        seq = Sequence(seq_id, np.zeros((prompt_len,), np.int32),
                       int(max_new), deadline, StreamHandle(seq_id),
                       self.engine.T)
        seq.remote_src = True
        seq.handle._cancel_cb = self._wake.set
        pool = self.engine.block_pool
        try:
            need = pool.blocks_for_tokens(prompt_len)
            got = pool.alloc(need, seq.seq_id)
            seq.blocks.extend(got)
            seq.table[:need] = got
            self.engine.install_kv(seq.table, kv)
        except BaseException:
            with self._lock:
                self._live -= 1
            if seq.blocks:
                pool.free(seq.blocks)
            raise
        seq.p0 = prompt_len
        self._first_token(seq, int(first_token))
        # same guard as _prefill_chunk: _first_token may have finished
        # the sequence (eos / max_new <= 1), in which case its blocks
        # are already freed and queueing it would run a second decode
        # lifecycle on a terminal sequence
        if seq.generated and seq.blocks:
            with self._lock:
                self._ready.append(seq)
            self._wake.set()
        return seq.handle

    # -- probes --------------------------------------------------------
    def live_count(self) -> int:
        """Sequences admitted and not yet terminal — INCLUDING ones
        only holding KV blocks between steps. Wired into
        MicroBatcher.add_idle_probe so a fleet drain waits for decode
        state, not just batch rows."""
        with self._lock:
            return self._live

    def snapshot(self) -> Dict:
        with self._lock:
            out = {"role": self.role,
                   "waiting": len(self._waiting),
                   "prefilling": len(self._prefilling),
                   "ready": len(self._ready),
                   "active": len(self._active),
                   "live": self._live,
                   "steps": self.steps,
                   "evictions": self.evictions}
        pool = self.engine.block_pool
        # graftlint: disable=config-namespace (statz snapshot fields)
        out["kv_blocks_used"] = pool.used
        out["kv_blocks_total"] = pool.capacity  # graftlint: disable=config-namespace (statz snapshot fields)
        out["compile"] = self.engine.compile_info()
        return out

    # -- internals -----------------------------------------------------
    def _finish(self, seq: Sequence, reason: str) -> None:
        """Terminal bookkeeping shared by every exit path: exactly the
        sequence's own blocks go back to the pool, its row frees, and
        its handle gets the terminal event. Idempotent: the first
        caller wins (the flag is checked-and-set under the lock), so a
        stop()-time sweep racing the scheduler loop can never double-
        free blocks, underflow _live, or emit a second terminal event."""
        with self._lock:
            if seq.finished:
                return
            seq.finished = True
            if seq.row is not None:
                self._active.pop(seq.row, None)
                self._free_rows.append(seq.row)
                seq.row = None
            self._live -= 1
        if seq.blocks:
            self.engine.block_pool.free(seq.blocks)
            seq.blocks = []
        if reason in ("eos", "length", "cancelled"):
            seq.handle.push({"event": "done", "reason": reason,
                             "tokens": list(seq.generated),
                             "seq": seq.seq_id})
        else:
            seq.handle.push({"event": "error", "reason": reason,
                             "error": f"sequence evicted: {reason}",
                             "tokens": list(seq.generated),
                             "seq": seq.seq_id})
        if reason not in ("eos", "length"):
            self.evictions += 1
            LEDGER.event("kv_evict", seq=seq.seq_id, reason=reason,
                         tokens=len(seq.generated))

    def _first_token(self, seq: Sequence, token: int) -> None:
        """Record + emit generated token 0 (from the prefill cell),
        finishing immediately when it already satisfies eos/limits."""
        seq.generated.append(token)
        seq.handle.push({"event": "token", "index": 0, "token": token})
        eos = self.cfg.eos
        if (eos >= 0 and token == eos) or seq.max_new <= 1:
            self._finish(seq, "eos" if eos >= 0 and token == eos
                         else "length")
        elif seq.p0 >= self.cfg.max_context:
            self._finish(seq, "length")

    def _ensure_blocks(self, seq: Sequence, n_tokens: int) -> bool:
        """Grow the sequence's table to cover ``n_tokens`` cache slots,
        evicting the most-recently-admitted block-holder under pool
        pressure. Returns False when SEQ ITSELF was the victim."""
        pool = self.engine.block_pool
        need = pool.blocks_for_tokens(n_tokens)
        while len(seq.blocks) < need:
            try:
                got = pool.alloc(1, seq.seq_id)
            except PoolExhausted:
                victim = self._pressure_victim()
                if victim is None or victim is seq:
                    self._drop_from_queues(seq)
                    self._finish(seq, "pressure")
                    return False
                self._drop_from_queues(victim)
                self._finish(victim, "pressure")
                continue
            seq.table[len(seq.blocks)] = got[0]
            seq.blocks.extend(got)
        return True

    def _pressure_victim(self) -> Optional[Sequence]:
        """Most-recently-admitted sequence holding blocks: FIFO progress
        is preserved (the oldest work always completes), so the loop
        cannot livelock — that is the no-starvation property the tests
        assert."""
        with self._lock:
            holders = [s for s in (list(self._prefilling)
                                   + list(self._ready)
                                   + list(self._active.values()))
                       if s.blocks]
        if not holders:
            return None
        return max(holders, key=lambda s: s.admitted_at)

    def _sweep_expired(self) -> None:
        """Deadline + cancel eviction across every queue."""
        now = time.monotonic()
        with self._lock:
            everyone = (list(self._waiting) + list(self._prefilling)
                        + list(self._ready) + list(self._active.values()))
        for seq in everyone:
            if seq.handle.cancelled:
                self._drop_from_queues(seq)
                self._finish(seq, "cancelled")
            elif seq.deadline is not None and now > seq.deadline:
                self._drop_from_queues(seq)
                self._finish(seq, "deadline")

    def _drop_from_queues(self, seq: Sequence) -> None:
        with self._lock:
            for q in (self._waiting, self._prefilling, self._ready):
                try:
                    q.remove(seq)
                except ValueError:
                    pass

    def _run(self) -> None:
        while not self._stopping.is_set():
            did_work = self._step_once()
            if not did_work:
                self._wake.wait(timeout=0.01)
                self._wake.clear()

    def _step_once(self) -> bool:
        """One scheduler iteration; returns whether anything ran."""
        self._sweep_expired()
        did = False
        # admit waiting -> prefilling (no block cost yet; the chunk loop
        # allocates as it writes)
        with self._lock:
            while self._waiting:
                self._prefilling.append(self._waiting.popleft())
        # one prefill chunk, oldest first — interleaved, never a loop
        with self._lock:
            seq = self._prefilling[0] if self._prefilling else None
        if seq is not None:
            did = True
            self._prefill_chunk(seq)
        # promote ready -> decode rows
        with self._lock:
            while self._ready and self._free_rows:
                s = self._ready.popleft()
                s.row = self._free_rows.pop()
                self._active[s.row] = s
        # one decode step over whoever holds a row
        if self._decode_step():
            did = True
        return did

    def _prefill_chunk(self, seq: Sequence) -> None:
        c = min(self.cfg.prefill_chunk, seq.prompt.size - seq.p0)
        if not self._ensure_blocks(seq, seq.p0 + c):
            self._drop_from_queues(seq)
            return
        ids = np.zeros((self.cfg.prefill_chunk,), np.int32)
        ids[:c] = seq.prompt[seq.p0:seq.p0 + c]
        token = self.engine.run_prefill(seq.table, ids, seq.p0, c)
        seq.p0 += c
        if seq.p0 < seq.prompt.size:
            return                      # more chunks to go
        self._drop_from_queues(seq)
        with self._lock:
            role, peer = self.role, self.peer
        if role == "prefill" and peer is not None:
            self._hand_off(seq, token, peer)
            return
        self._first_token(seq, token)
        if seq.generated and seq.blocks:
            with self._lock:
                self._ready.append(seq)

    def _hand_off(self, seq: Sequence, first_token: int,
                  peer: Tuple[str, int]) -> None:
        """Ship cache + first token to the decode plane; local blocks
        free IMMEDIATELY (the whole point of disaggregation), and a
        relay thread pumps the peer's events into the local handle."""
        from .handoff import ship_prefill
        kv = self.engine.extract_kv(seq.table)
        self.engine.block_pool.free(seq.blocks)
        seq.blocks = []
        left_ms = 0.0
        if seq.deadline is not None:
            left_ms = max(1.0, (seq.deadline - time.monotonic()) * 1e3)
        LEDGER.event("prefill_handoff", seq=seq.seq_id,
                     prompt_len=int(seq.prompt.size),
                     peer=f"{peer[0]}:{peer[1]}")
        with self._lock:
            self._live -= 1     # local custody ends; relay owns the handle

        def relay():
            ship_prefill(peer, int(seq.prompt.size), int(first_token),
                         seq.max_new, left_ms, kv, seq.handle)

        t = threading.Thread(target=relay, daemon=True,
                             name=f"lm-handoff-{seq.seq_id}")
        # prune finished relays so a long-lived prefill replica doesn't
        # accumulate one dead thread object per handed-off sequence
        self._ship_threads = [s for s in self._ship_threads
                              if s.is_alive()]
        self._ship_threads.append(t)
        t.start()

    def _decode_step(self) -> bool:
        with self._lock:
            rows = dict(self._active)
        if not rows:
            return False
        for seq in list(rows.values()):
            # the step writes cache entry p0 + len(generated) - 1, so
            # the table must cover p0 + len(generated) slots — the SAME
            # ensure() the whole-request path does before its step
            self._ensure_blocks(seq, seq.p0 + len(seq.generated))
        with self._lock:
            rows = dict(self._active)   # pressure evictions applied
        if not rows:
            return False
        B, T = self.cfg.max_seqs, self.engine.T
        ids = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, T), np.int32)
        lengths = np.zeros((B,), np.int32)
        for row, seq in rows.items():
            # generated[i] feeds at position p0 + i: the last appended
            # token goes in at p0 + len - 1 and the cache then holds
            # p0 + len entries — identical per-row inputs to
            # generate_whole's loop, which is what makes greedy tokens
            # bit-identical between the two paths
            L = seq.p0 + len(seq.generated) - 1
            ids[row] = seq.generated[-1]
            positions[row] = L
            tables[row] = seq.table
            lengths[row] = L + 1
        toks = self.engine.run_decode(ids, positions, tables, lengths)
        self.steps += 1
        eos = self.cfg.eos
        for row, seq in rows.items():
            t = int(toks[row])
            seq.generated.append(t)
            seq.handle.push({"event": "token",
                             "index": len(seq.generated) - 1, "token": t})
            if eos >= 0 and t == eos:
                self._finish(seq, "eos")
            elif len(seq.generated) >= seq.max_new:
                self._finish(seq, "length")
            elif seq.p0 + len(seq.generated) - 1 >= self.cfg.max_context:
                # the next token would feed at a position outside the
                # context window — same cutoff as generate_whole's
                # `L < max_context` loop condition
                self._finish(seq, "length")
        return True
