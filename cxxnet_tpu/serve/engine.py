"""InferenceEngine: frozen params + bucketed cache of compiled predictors.

The training side compiles ONE train step per shape and reuses it for the
whole run (trainer.py); serving traffic has no fixed shape, so the engine
quantizes request batches onto a small set of power-of-two **shape
buckets**, pads up to the bucket, and keeps an LRU of jit-compiled
executables keyed by ``(bucket_rows, output_kind[, node])`` (the input
shape is an engine-level constant). Steady
state traffic therefore never recompiles: the cache-miss counter equals
the number of distinct buckets exercised.

Eval-mode rows are independent (batch_norm uses running stats at eval), so
zero-padding rows up to the bucket cannot perturb the real rows — the
padded tail is sliced off before results leave the engine.

Supported parallelism: the std (GSPMD dp/tp) path. Sequence- and
pipeline-parallel trainers are training-topology artifacts; serving them
is a later PR (shard across ``parallel/mesh.py``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..config import ConfigPairs, parse_config_string, parse_policy
from ..resilience import failpoints
from ..telemetry.trace import TRACER
from ..trainer import Trainer
from .. import checkpoint as ckpt
from .stats import ServingStats

# output kinds mirroring the three cxxnet offline task modes
_KINDS = ("predict", "raw", "extract")


def version_name(round_counter: int) -> str:
    """Canonical model-version id for a checkpoint round (mirrors the
    ``%04d.model`` filename convention). Weights that never came from a
    checkpoint are version ``"init"`` — everywhere, so a version pin
    means the same thing against a single engine and a fleet."""
    return "r%04d" % int(round_counter)


def restore_inference_blob(trainer: Trainer, blob) -> None:
    """Place an already-loaded inference blob (params + layer state,
    no optimizer) onto ``trainer`` — shared by the serve driver branch,
    the fleet pool builder, and :func:`restore_inference_state`."""
    ckpt.check_structure(blob["meta"],
                         trainer.graph.structure_signature())
    trainer.params, trainer.net_state = trainer._place(
        blob["params"], blob["state"])
    trainer.round_counter = blob["meta"]["round"]
    trainer.epoch_counter = blob["meta"]["epoch"]


def negotiate_blob(blob, dtype: Optional[str]):
    """Dtype negotiation between a loaded inference blob and the
    engine's requested serving dtype (doc/tasks.md "Quantized serving &
    cascade"):

    - ``int8`` engine + quantized round: serve as-is (the int8 path).
    - ``int8`` engine + plain round: ERROR — silent on-the-fly weight
      quantization would skip calibration and the drift verdict; run
      tools/quantize.py and serve the derived round.
    - fp engine + quantized round: dequantize on load (scales folded
      back into f32 ``wmat``) — a flagship replica can always read a
      quantized artifact, it just pays fp compute.
    - fp engine + plain round: pass through.
    """
    from ..quant import dequantize_blob, is_quantized_params
    want_int8 = bool(dtype) and str(dtype).lower() == "int8"
    quantized = is_quantized_params(blob["params"])
    if want_int8 and not quantized:
        raise ValueError(
            "serve_dtype=int8 but the checkpoint round is not "
            "quantized (no __quant_meta__/wmat_scale leaves); run "
            "tools/quantize.py to derive an int8 round first")
    if quantized and not want_int8:
        return dequantize_blob(blob)
    return blob


def restore_inference_state(trainer: Trainer, model_path: str,
                            verify: bool = True) -> None:
    """Restore params + layer state onto ``trainer`` from a checkpoint
    WITHOUT materializing optimizer state (momentum buffers would roughly
    double the model's device bytes, and an engine never steps the
    optimizer) — shared by InferenceEngine.from_checkpoint and the
    ``task = serve`` driver branch. ``verify=False`` when the caller
    just verified the archive (the continue=1 resume scan)."""
    restore_inference_blob(
        trainer, ckpt.load_for_inference(model_path, verify=verify))


def _parse_buckets(val: Union[str, Sequence[int], None],
                   max_batch: int, dp: int) -> List[int]:
    """Bucket ladder: explicit comma list, or powers of two from the
    data-parallel degree up to ``max_batch``."""
    if val:
        if isinstance(val, str):
            buckets = sorted({int(x) for x in val.split(",") if x.strip()})
        else:
            buckets = sorted({int(x) for x in val})
    else:
        buckets = []
        b = max(1, dp)
        while b < max_batch:
            buckets.append(b)
            b *= 2
        buckets.append(max_batch)
    if not buckets or buckets[0] < 1:
        raise ValueError(f"invalid serve buckets {buckets!r}")
    for b in buckets:
        if b % max(1, dp):
            raise ValueError(
                f"serve bucket {b} not divisible by data-parallel degree "
                f"{dp} (pick buckets that tile the mesh)")
    return buckets


class InferenceEngine:
    """Wrap a trained :class:`Trainer` into a frozen predict service.

    ``predict`` / ``predict_raw`` / ``extract`` match the three cxxnet
    task modes (pred / pred_raw / extract_feature). Thread-safe: the
    compile cache takes a lock; jitted calls themselves are re-entrant.
    """

    def __init__(self, trainer: Trainer,
                 buckets: Union[str, Sequence[int], None] = None,
                 max_batch: int = 64, cache_size: int = 16,
                 stats: Optional[ServingStats] = None,
                 layout: str = "NCHW",
                 dtype: Optional[str] = None):
        if trainer.params is None:
            raise ValueError("trainer has no params: init_model()/"
                             "load_model() before wrapping")
        if trainer.mesh.seq_parallel > 1 or trainer.mesh.pipeline_parallel > 1:
            raise ValueError("serve: std (dp/tp) trainers only; sp/pp "
                             "serving is not supported")
        if trainer.graph.extra_data_num:
            raise ValueError("serve: graphs with extra_data are not "
                             "servable (single-input requests)")
        self.trainer = trainer
        self.stats = stats or ServingStats()
        self.layout = layout
        # serving compute dtype: an engine-level constant (part of no
        # cache key — every compiled cell shares it). Defaults to the
        # net's configured policy; an explicit ``dtype`` overrides, so a
        # checkpoint trained fp32 can SERVE bf16 (params are fp32
        # masters either way — the cast happens inside the compiled
        # predictor). Responses always leave as the policy's fp32
        # output dtype. ``dtype="int8"`` selects the quantized path:
        # weights must be a PTQ-derived round (scales in the params
        # tree, quant/ptq.py); non-quantized interior layers and the
        # dequant epilogue run f32.
        self.serve_int8 = bool(dtype) and str(dtype).lower() == "int8"
        if self.serve_int8:
            self.compute_dtype = parse_policy("float32").compute_dtype
        else:
            self.compute_dtype = (parse_policy(dtype).compute_dtype
                                  if dtype else trainer.net.compute_dtype)
        from ..quant import is_quantized_params
        quantized = is_quantized_params(trainer.params)
        if self.serve_int8 and not quantized:
            raise ValueError(
                "serve_dtype=int8 but the loaded params are not "
                "quantized (no wmat_scale leaves); run tools/quantize.py "
                "and serve the derived round")
        if quantized and not self.serve_int8:
            raise ValueError(
                "params are int8-quantized but the engine dtype is "
                f"{dtype or 'the net policy'}; set serve_dtype=int8 or "
                "dequantize the blob first (serve.engine.negotiate_blob)")
        dp = trainer.mesh.data_parallel
        self.max_batch = int(max_batch)
        self.buckets = _parse_buckets(buckets, self.max_batch, dp)
        if self.buckets[-1] > self.max_batch:
            # max_batch is the operator's per-dispatch memory/latency
            # cap; a bucket above it would silently raise that cap
            raise ValueError(
                f"serve bucket {self.buckets[-1]} exceeds max_batch "
                f"{self.max_batch}; raise serve_max_batch or drop the "
                "bucket")
        if self.max_batch > self.buckets[-1]:
            # an explicit ladder must still honor max_batch: the batcher
            # sizes dispatches up to max_batch, and a dispatch larger
            # than the top bucket could never run as one device call
            if self.max_batch % max(1, dp):
                raise ValueError(
                    f"serve max_batch {self.max_batch} not divisible by "
                    f"data-parallel degree {dp}")
            self.buckets.append(self.max_batch)
        self.input_shape = tuple(trainer.graph.input_shape)  # (c, y, x)
        self._cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._cache_cap = int(cache_size)
        if self._cache_cap < 1:
            raise ValueError(
                f"serve cache_size must be >= 1, got {self._cache_cap}")
        self._lock = threading.Lock()
        # weights identity: (params, net_state) must be read as a PAIR at
        # dispatch time — a hot reload (serve/reload.py) swaps both under
        # this lock, and a dispatch that read new params with old BN
        # running stats would serve a model that never existed
        self._weights_lock = threading.Lock()
        # weights provenance: the checkpoint round + short digest this
        # engine is serving, maintained by swap_weights (fleet replicas
        # surface it as their model version). weights_version stays
        # "init" until a checkpoint actually lands (from_checkpoint,
        # swap_weights, or the serve driver's restore) — a random-init
        # smoke engine must not answer to a round-shaped version pin
        self.weights_round = int(trainer.round_counter)
        self.weights_digest = ""
        self.weights_version = "init"
        self.stats.record_cache(size=0, capacity=self._cache_cap)

    # -- construction ----------------------------------------------------
    @classmethod
    def from_checkpoint(cls, cfg: Union[str, ConfigPairs], model_path: str,
                        **kw) -> "InferenceEngine":
        """Build a trainer from a net config and restore inference state
        from ``model_path`` WITHOUT materializing optimizer state
        (checkpoint.load_for_inference) — an engine never steps the
        optimizer, and momentum buffers double a model's device bytes."""
        pairs = parse_config_string(cfg) if isinstance(cfg, str) \
            else list(cfg)
        tr = Trainer(pairs)
        blob = ckpt.load_for_inference(model_path)
        restore_inference_blob(tr, negotiate_blob(blob, kw.get("dtype")))
        eng = cls(tr, **kw)
        eng.weights_digest = ckpt.blob_digest(blob["meta"])
        # int8 engines serve the DERIVED artifact: the version carries
        # the dtype suffix so a pin can never conflate the quantized
        # round with its fp source (cascade tiers route on this)
        eng.weights_version = version_name(tr.round_counter) \
            + ("-int8" if eng.serve_int8 else "")
        return eng

    # -- shape plumbing --------------------------------------------------
    def _to_input(self, data: np.ndarray) -> np.ndarray:
        """Accept (n, features) flat, or 4-D in the engine's layout
        (NCHW default, matching wrapper.Net) — returns NHWC float32.
        Layout conversion itself is wrapper._to_nhwc (one definition of
        the convention); the engine adds what only it can check: flat
        row width against the model's input_shape, and the reshape of
        flat rows onto a non-flat (c,y,x) input."""
        from ..wrapper import _to_nhwc
        data = np.asarray(data)
        if data.dtype.kind not in "fiub":
            # admission-time dtype assert: a non-numeric payload (object
            # arrays from ragged/str JSON) must 400 here, not explode
            # inside the compiled call (batcher.submit routes through
            # this before any queueing)
            raise ValueError(
                f"request payload dtype {data.dtype} is not numeric")
        data = data.astype(np.float32, copy=False)
        if self.serve_int8 and not np.isfinite(data).all():
            # int8 replicas quantize activations against a calibrated
            # static scale: a non-finite row (e.g. an fp64 payload that
            # overflowed the float32 cast) would silently saturate the
            # quantizer inside the compiled call — reject at admission
            raise ValueError(
                "request payload contains non-finite values after "
                "float32 cast (int8 replica admission check)")
        c, y, x = self.input_shape
        if data.ndim == 2:
            if data.shape[1] != c * y * x:
                raise ValueError(
                    f"flat request row width {data.shape[1]} != model "
                    f"input {c}*{y}*{x}")
            if not (c == 1 and y == 1):
                # flat rows in NCHW element order onto an image input
                return _to_nhwc(data.reshape(-1, c, y, x), "NCHW")
        return np.ascontiguousarray(_to_nhwc(data, self.layout))

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (largest bucket for oversize chunks)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _pad(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        if rows.shape[0] == bucket:
            return rows
        pad = np.zeros((bucket - rows.shape[0],) + rows.shape[1:],
                       rows.dtype)
        return np.concatenate([rows, pad], axis=0)

    # -- compile cache ---------------------------------------------------
    def _compiled(self, bucket: int, kind: str, node: Optional[str]):
        """LRU lookup of the jitted executable for one (bucket, kind[,
        node]) cell; a miss builds (and counts) a fresh jit closure —
        the compile itself lands on the first call, i.e. inside the
        miss request."""
        key = (bucket, kind, node)
        with self._lock:
            fn = self._cache.get(key)
            if fn is not None:
                self._cache.move_to_end(key)
                self.stats.record_cache(hit=True, size=len(self._cache))
                return fn
            evicted = False
            while len(self._cache) >= self._cache_cap:
                self._cache.popitem(last=False)
                evicted = True
            fn = self._build(kind, node)
            self._cache[key] = fn
            self.stats.record_cache(hit=False, size=len(self._cache),
                                    evicted=evicted)
            return fn

    def _build(self, kind: str, node: Optional[str]):
        import jax
        import jax.numpy as jnp
        net = self.trainer.net
        cdt = self.compute_dtype
        # responses leave in the policy's fp32 output dtype even when the
        # interior ran bf16/fp16 (callers see stable numerics; JSON/C
        # marshalling stays float32 everywhere)
        out_dt = net.policy.output_dtype

        if kind == "extract":
            def fn(params, state, data):
                res = net.apply(params, state, data, train=False,
                                capture_nodes=True, compute_dtype=cdt)
                v = res.out if node in ("top", "top[-1]") \
                    else res.nodes[node]
                return v.reshape(v.shape[0], -1).astype(out_dt)
        elif kind == "raw":
            def fn(params, state, data):
                res = net.apply(params, state, data, train=False,
                                compute_dtype=cdt)
                return res.out.reshape(res.out.shape[0], -1).astype(out_dt)
        else:                                   # "predict"
            def fn(params, state, data):
                res = net.apply(params, state, data, train=False,
                                compute_dtype=cdt)
                out = res.out.reshape(res.out.shape[0], -1)
                if out.shape[1] == 1:
                    return out[:, 0].astype(out_dt)
                return jnp.argmax(out, axis=1).astype(out_dt)
        return jax.jit(fn)

    # -- inference -------------------------------------------------------
    def run_padded(self, rows_nhwc: np.ndarray, kind: str,
                   node: Optional[str] = None) -> np.ndarray:
        """One device call on pre-shaped NHWC rows: pad to the bucket,
        run the cached executable, slice the real rows back out. This is
        the batcher's dispatch entry — it must stay a SINGLE device call
        per invocation."""
        if kind not in _KINDS:
            raise ValueError(f"unknown output kind {kind!r}")
        # the wedged-device stand-in chaos tests use to trip the serve
        # circuit breaker (batcher counts consecutive dispatch failures)
        failpoints.check("serve.infer", RuntimeError)
        n = rows_nhwc.shape[0]
        bucket = self.bucket_for(n)
        if n > bucket:
            # never truncate silently: a short result would corrupt the
            # batcher's per-request scatter offsets
            raise ValueError(
                f"run_padded: {n} rows exceed the largest bucket "
                f"{bucket}; chunk to max_batch first")
        tr = self.trainer
        with TRACER.span("serve.infer", cat="serve",
                         args={"rows": int(n), "bucket": int(bucket),
                               "kind": kind}):
            fn = self._compiled(bucket, kind, node)
            padded = self._pad(rows_nhwc, bucket)
            data = tr.mesh.shard_batch(padded)
            # params + net_state read as a pair: a concurrent
            # swap_weights must never interleave between the two reads
            with self._weights_lock:
                params, state = tr.params, tr.net_state
            out = np.asarray(fn(params, state, data))
        return out[:n]

    def _run(self, data, kind: str, node: Optional[str] = None
             ) -> np.ndarray:
        rows = self._to_input(data)
        outs = []
        off = 0
        while off < rows.shape[0]:       # oversize: chunk by max bucket
            chunk = rows[off:off + self.max_batch]
            outs.append(self.run_padded(chunk, kind, node))
            off += chunk.shape[0]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def predict(self, data) -> np.ndarray:
        """Class predictions (argmax; raw scalar for 1-col outputs) —
        ``task = pred``."""
        return self._run(data, "predict")

    def predict_raw(self, data) -> np.ndarray:
        """Full top-node rows (e.g. softmax probabilities) —
        ``task = pred_raw``."""
        return self._run(data, "raw")

    def extract(self, data, node_name: str) -> np.ndarray:
        """Named node activations ('top' = final node) —
        ``task = extract_feature``."""
        return self._run(data, "extract", node_name)

    # -- hot weight reload -----------------------------------------------
    def swap_weights(self, params, net_state, round_counter: int,
                     digest: str = "") -> None:
        """Replace the served weights in place — the hot-reload primitive
        (serve/reload.py). ``params``/``net_state`` are host pytrees from
        a verified checkpoint blob; placement uses the SAME sharded-put
        path a checkpoint restore uses, so TP-sharded engines reload
        correctly. The compiled executables are untouched: they close
        over shapes only and take weights as arguments, so a swap costs
        one device transfer and zero recompiles. Callers are expected to
        have structure-checked the blob (checkpoint.check_structure) —
        the reload watcher does."""
        from ..quant import is_quantized_params
        if is_quantized_params(params) != self.serve_int8:
            # hot-reload dtype negotiation: an int8 replica must never
            # silently swap in a plain fp round (and vice versa) — the
            # compiled closures bake the quantized/fp layer path in
            raise ValueError(
                "swap_weights: params quantization does not match the "
                f"engine dtype (serve_int8={self.serve_int8}); "
                "negotiate the blob first (serve.engine.negotiate_blob)")
        tr = self.trainer
        placed_p, placed_s = tr._place(params, net_state)
        # swap both references under the dispatch read lock so no device
        # call ever sees new params with old state
        with self._weights_lock:
            tr.params, tr.net_state = placed_p, placed_s
            tr.round_counter = int(round_counter)
            self.weights_round = int(round_counter)
            self.weights_digest = digest
            self.weights_version = version_name(round_counter) \
                + ("-int8" if self.serve_int8 else "")

    # -- introspection ---------------------------------------------------
    def node_shape(self, node_name: str = "top") -> Tuple[int, int, int]:
        return self.trainer.node_shape(node_name)

    def cache_info(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._cache), "capacity": self._cache_cap,
                    "hits": self.stats.cache_hits,
                    "misses": self.stats.cache_misses,
                    "evictions": self.stats.cache_evictions}
