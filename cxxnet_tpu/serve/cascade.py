"""Cascade inference: a confidence router in front of a two-tier fleet.

The cheap tier (int8-quantized replicas, ops/fused_quant.py) answers
every request first; rows whose prediction confidence clears
``cascade_threshold`` are final, the rest escalate to the flagship
(full-precision) tier. The cost model is the classic cascade win:
every row pays the cheap tier, only the escalated fraction pays the
flagship, so cost-per-request ~= fast_latency + escalation_rate *
flagship_latency — tools/loadgen.py measures exactly that line for
``SERVE_r03.json``.

Confidence per row over the fast tier's raw output (softmax probs):

- ``margin``:  p1 - p2 (top-two gap), the standard cascade rule;
- ``entropy``: 1 - H(p)/log(k), normalized so 1 = one-hot certain.

Rows from models with a single output column (regression heads) have
no class distribution to be confident about — they never escalate.

:class:`CascadeRouter` IS a :class:`ReplicaPool` over both tiers'
replicas (tier membership = model version: the quantized round serves
as ``rNNNN-int8``, the source round as ``rNNNN``), so the ServeServer
pool surface — health, /statz, drain, version pinning, per-version
outcome stats — works unchanged; only ``submit`` adds the routing.
Version-pinned requests and ``extract`` (feature taps have no
confidence semantics) bypass the cascade and route directly.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..config import QuantConfig
from ..telemetry.ledger import LEDGER
from ..telemetry.registry import REGISTRY
from .fleet import Replica, ReplicaPool

_TINY = 1e-12


def row_confidence(probs: np.ndarray, metric: str = "margin") -> np.ndarray:
    """Per-row confidence in [0, 1] from raw output rows. Rows are
    defensively renormalized (the fast tier's top node is softmax in
    every served graph, but a linear head must not produce NaN
    confidences)."""
    p = np.asarray(probs, np.float64)
    if p.ndim != 2:
        p = p.reshape(p.shape[0], -1)
    k = p.shape[1]
    if k < 2:
        return np.ones(p.shape[0])
    p = np.clip(p, 0.0, None)
    p = p / np.maximum(p.sum(axis=1, keepdims=True), _TINY)
    if metric == "entropy":
        h = -np.sum(p * np.log(np.maximum(p, _TINY)), axis=1)
        return 1.0 - h / np.log(k)
    top2 = np.partition(p, k - 2, axis=1)[:, -2:]
    return top2[:, 1] - top2[:, 0]


class CascadeRouter(ReplicaPool):
    """Two-tier pool with confidence routing (see module docstring).
    Build with :meth:`build_two_tier`; or pass pre-built replicas plus
    the two tier version strings directly (tests)."""

    def __init__(self, replicas: Sequence[Replica], *,
                 fast_version: str, flagship_version: str,
                 qc: QuantConfig, admission_control: bool = True):
        super().__init__(replicas, admission_control=admission_control)
        if fast_version == flagship_version:
            raise ValueError(
                "cascade tiers must serve distinct versions, both are "
                f"{fast_version!r} (the quantized round serves with an "
                "-int8 suffix — did both tiers load the same blob?)")
        for want in (fast_version, flagship_version):
            if not any(r.version == want for r in self.replicas):
                raise ValueError(
                    f"cascade: no replica serves tier version {want!r}; "
                    f"have {sorted(self.versions())}")
        self.fast_version = fast_version
        self.flagship_version = flagship_version
        self.threshold = float(qc.cascade_threshold)
        self.metric = qc.cascade_metric
        self._clock = threading.Lock()
        self._cstats = {"requests": 0, "requests_escalated": 0,
                        "rows": 0, "rows_escalated": 0, "failed": 0}
        self._c_rows = REGISTRY.counter(
            "cxxnet_cascade_rows_total",
            "Cascade rows by final answering tier",
            labels=("pool", "tier"))
        self._g_esc = REGISTRY.gauge(
            "cxxnet_cascade_escalation_rate",
            "Fraction of cascade rows escalated to the flagship tier",
            labels=("pool",))

    # -- construction ----------------------------------------------------
    @classmethod
    def build_two_tier(cls, cfg: Any, *, flagship_blob: Dict[str, Any],
                       fast_blob: Dict[str, Any], qc: QuantConfig,
                       n_flagship: int = 1, n_fast: int = 1,
                       flagship_digest: str = "", fast_digest: str = "",
                       flagship_dtype: Optional[str] = None,
                       admission_control: bool = True,
                       silent: bool = False, **pool_kw) -> "CascadeRouter":
        """Build both tiers over the same net config: ``n_fast``
        int8 replicas on the quantized blob plus ``n_flagship``
        full-precision replicas on the source blob, merged into one
        router. Device slicing happens per tier (on CPU sessions the
        tiers share the host device, which is exactly the measurement
        mode SERVE_r03 documents)."""
        fast = ReplicaPool.build(
            cfg, n_fast, blob=fast_blob, digest=fast_digest,
            dtype="int8", admission_control=admission_control,
            silent=silent, **pool_kw)
        flagship = ReplicaPool.build(
            cfg, n_flagship, blob=flagship_blob, digest=flagship_digest,
            dtype=flagship_dtype, admission_control=admission_control,
            silent=silent, **pool_kw)
        replicas: List[Replica] = list(fast.replicas) + \
            list(flagship.replicas)
        for i, rep in enumerate(replicas):
            rep.idx = i
        return cls(replicas,
                   fast_version=fast.replicas[0].version,
                   flagship_version=flagship.replicas[0].version,
                   qc=qc, admission_control=admission_control)

    # -- routing ---------------------------------------------------------
    def submit(self, data, kind: str = "predict",
               node: Optional[str] = None,
               timeout_ms: Optional[float] = None,
               version: Optional[str] = None):
        """Confidence-routed submit. ``predict``/``raw`` requests run
        the cascade; an explicit ``version`` pin or ``extract`` routes
        directly (both legs still land in the per-version outcome
        stats via the base pool)."""
        if version is not None or kind == "extract":
            return super().submit(data, kind, node, timeout_ms, version)
        rows = np.asarray(data)
        out: "Future[np.ndarray]" = Future()
        fast_fut = super().submit(rows, "raw", None, timeout_ms,
                                  self.fast_version)
        fast_fut.add_done_callback(
            lambda f: self._on_fast(f, rows, kind, timeout_ms, out))
        return out

    def _finalize(self, out: Future, result=None, exc=None) -> None:
        if exc is not None:
            with self._clock:
                self._cstats["failed"] += 1
            out.set_exception(exc)
        else:
            out.set_result(result)

    def _on_fast(self, f: Future, rows: np.ndarray, kind: str,
                 timeout_ms: Optional[float], out: Future) -> None:
        exc = f.exception()
        if exc is not None:
            self._finalize(out, exc=exc)
            return
        try:
            probs = np.asarray(f.result())
            conf = row_confidence(probs, self.metric)
            esc = conf < self.threshold
            n, n_esc = len(conf), int(esc.sum())
            with self._clock:
                self._cstats["requests"] += 1
                self._cstats["rows"] += n
                self._cstats["rows_escalated"] += n_esc
                if n_esc:
                    self._cstats["requests_escalated"] += 1
                rate = self._cstats["rows_escalated"] \
                    / max(1, self._cstats["rows"])
            self._c_rows.labels(self.instance, "fast").inc(n - n_esc)
            self._g_esc.labels(self.instance).set(rate)
            if not n_esc:
                self._finalize(out, self._fast_answer(probs, kind))
                return
            self._c_rows.labels(self.instance, "flagship").inc(n_esc)
            LEDGER.event("cascade_escalate", rows=n_esc, total=n,
                         min_conf=round(float(conf.min()), 4),
                         threshold=self.threshold, metric=self.metric)
            flag_fut = ReplicaPool.submit(
                self, rows[esc], kind, None, timeout_ms,
                self.flagship_version)
            flag_fut.add_done_callback(
                lambda g: self._on_flagship(g, probs, esc, kind, out))
        except Exception as e:                  # noqa: BLE001
            self._finalize(out, exc=e)

    def _on_flagship(self, g: Future, probs: np.ndarray,
                     esc: np.ndarray, kind: str, out: Future) -> None:
        exc = g.exception()
        if exc is not None:
            self._finalize(out, exc=exc)
            return
        try:
            merged = self._fast_answer(probs, kind)
            flag = np.asarray(g.result())
            merged[esc] = flag
            self._finalize(out, merged)
        except Exception as e:                  # noqa: BLE001
            self._finalize(out, exc=e)

    @staticmethod
    def _fast_answer(probs: np.ndarray, kind: str) -> np.ndarray:
        """Fast-tier rows in the requested output kind (matching the
        engine's predict semantics: argmax, raw scalar for 1-col)."""
        if kind == "raw":
            return np.array(probs, np.float32)
        p = probs.reshape(probs.shape[0], -1)
        if p.shape[1] == 1:
            return p[:, 0].astype(np.float32)
        return np.argmax(p, axis=1).astype(np.float32)

    # -- introspection ---------------------------------------------------
    def cascade_stats(self) -> Dict[str, Any]:
        with self._clock:
            s = dict(self._cstats)
        s.update(
            threshold=self.threshold, metric=self.metric,
            fast_version=self.fast_version,
            flagship_version=self.flagship_version,
            escalation_rate=round(
                s["rows_escalated"] / max(1, s["rows"]), 6))
        return s

    def snapshot(self) -> Dict[str, Any]:
        out = super().snapshot()
        out["cascade"] = self.cascade_stats()
        return out
