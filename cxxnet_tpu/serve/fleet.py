"""Serving fleet: a replica pool with SLO-aware routing.

The reference cxxnet's production story was "one binary, many devices" —
one pthread + CUDA stream per GPU behind a parameter server
(neural_net-inl.hpp:324-658) — but only for training; serving was
offline batch predict. This module applies the same shape to ONLINE
traffic: N independent :class:`InferenceEngine` replicas, each owning a
slice of the device mesh plus its own micro-batcher, circuit breaker and
SLO tracker, behind a router that picks per request.

Routing policy (``ReplicaPool.pick``), in order:

1. **version pin** — a request carrying a model version (A/B testing)
   only considers replicas serving that version;
2. **availability** — replicas that are draining/reloading/down or whose
   breaker is open are skipped entirely;
3. **admission control** — if every available replica is *degraded*
   (SLO burn rate at/over the paging threshold, or queue near its
   budget), the request is rejected up front with
   :class:`AllReplicasDegraded` (HTTP 503): shedding load early is how
   the error budget stops burning — this is the balancer side of the
   ``serve_slo_*`` signal (ROADMAP item 3);
4. **least load** — among the healthy survivors, the replica with the
   fewest queued rows wins (round-robin rotation breaks ties so equal
   queues don't starve high-index replicas).

Hot weight reload (serve/reload.py) swaps replicas one at a time: a
DRAINING replica keeps serving what it already admitted but receives no
new work, so a rolling reload drops zero requests. A/B pinning falls out
of the same machinery — reload only a canary subset and two checkpoint
versions serve side by side, with per-version stats and deterministic
``version`` routing.

Pure stdlib threading; every public method is thread-safe.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from ..resilience import CircuitBreaker
from ..telemetry.disttrace import DISTTRACE
from ..telemetry.ledger import LEDGER
from ..telemetry.registry import REGISTRY
from ..telemetry.slo import SLOTracker
from .batcher import MicroBatcher
from .engine import InferenceEngine, version_name
from .stats import ServingStats

# replica lifecycle states (numeric encoding is what the
# cxxnet_serve_replica_state gauge exports)
UP, DRAINING, RELOADING, DOWN = "up", "draining", "reloading", "down"
_STATE_CODE = {UP: 0, DRAINING: 1, RELOADING: 2, DOWN: 3}

_POOL_SEQ = itertools.count()

__all__ = ["Replica", "ReplicaPool", "NoHealthyReplica",
           "AllReplicasDegraded", "UnknownVersion", "version_name",
           "UP", "DRAINING", "RELOADING", "DOWN"]


class NoHealthyReplica(RuntimeError):
    """Every candidate replica is out of rotation (down, draining, or
    breaker-open): fail fast, retry later (HTTP 503)."""


class AllReplicasDegraded(NoHealthyReplica):
    """Admission control: every available replica is degraded (SLO burn
    / queue saturation) — shed the request instead of making the burn
    worse (HTTP 503)."""


class UnknownVersion(ValueError):
    """The request pinned a model version no replica serves (HTTP 400)."""


class Replica:
    """One engine + batcher + breaker + SLO tracker, with a lifecycle
    state the router keys on. Created by :meth:`ReplicaPool.build`."""

    def __init__(self, idx: int, engine: InferenceEngine,
                 batcher: MicroBatcher,
                 breaker: Optional[CircuitBreaker],
                 slo: Optional[SLOTracker],
                 degraded_queue_frac: float = 0.8,
                 slo_burn_degraded: float = 2.0):
        self.idx = int(idx)
        self.engine = engine
        self.batcher = batcher
        self.breaker = breaker
        self.slo = slo
        self.degraded_queue_frac = float(degraded_queue_frac)
        self.slo_burn_degraded = float(slo_burn_degraded)
        # LM serving plane (serve/lm LMScheduler), attached by
        # ReplicaPool.attach_lm; None on predict-only fleets
        self.lm = None
        self._state = UP
        # serializes request admission against lifecycle transitions:
        # a submit holds it across the state/version re-check AND the
        # batcher enqueue, and a reload takes it to flip DRAINING — so
        # a request picked for version X can never be admitted after
        # the replica started draining toward version Y (the
        # pick-to-submit TOCTOU)
        self.admission_lock = threading.Lock()
        self._g_state = REGISTRY.gauge(
            "cxxnet_serve_replica_state",
            "Replica lifecycle state (0=up 1=draining 2=reloading 3=down)",
            labels=("engine",)).labels(engine.stats.instance)
        self._g_state.set(0)

    # -- state -----------------------------------------------------------
    @property
    def version(self) -> str:
        """The model version this replica serves — one source of truth
        (the engine's weights provenance), so a swap can never leave
        router-visible version state out of sync with the weights."""
        return self.engine.weights_version

    @property
    def state(self) -> str:
        return self._state

    def set_state(self, new: str) -> None:
        """Lifecycle transition + gauge + ledger ``replica_state`` event
        (the serving analog of breaker_transition)."""
        if new not in _STATE_CODE:
            raise ValueError(f"unknown replica state {new!r}")
        old, self._state = self._state, new
        if old == new:
            return
        self._g_state.set(_STATE_CODE[new])
        # W3C trace context rides along when a span is current (a
        # reload sweep's drain, a traced request's drain wait) so
        # tools/trace_assemble.py joins the transition to the requests
        # it affected; LEDGER.event stamps trace_id itself
        tp = DISTTRACE.current_traceparent()
        LEDGER.event("replica_state", replica=self.idx,
                     engine=self.engine.stats.instance,
                     from_state=old, to_state=new, version=self.version,
                     **({"traceparent": tp} if tp else {}))

    # -- router signals --------------------------------------------------
    def alive(self) -> bool:
        return self.batcher._thread.is_alive()

    def available(self) -> bool:
        """In rotation: UP, worker alive, breaker not hard-open. A
        breaker past its reset timeout reads half_open and stays
        available — the recovery probe needs a trickle of traffic."""
        if self._state != UP or not self.alive():
            return False
        return self.breaker is None \
            or self.breaker.effective_state() != "open"

    def queue_frac(self) -> float:
        return self.batcher.queued_rows / max(1, self.batcher.max_queue_rows)

    def burn_rate(self) -> float:
        return self.slo.burn_rate() if self.slo is not None else 0.0

    def degraded(self) -> bool:
        """Impaired but still serving: the admission-control predicate.
        Mirrors the single-engine /healthz degraded clause (queue near
        budget, breaker probing, SLO burn at/over the paging line)."""
        if self.breaker is not None \
                and self.breaker.effective_state() == "half_open":
            return True
        return self.queue_frac() >= self.degraded_queue_frac \
            or self.burn_rate() >= self.slo_burn_degraded

    def health(self) -> str:
        """``ok | degraded | open | down`` — same vocabulary as the
        single-engine /healthz (a draining/reloading replica reads
        degraded: deliberately impaired, not broken)."""
        if not self.alive():
            return "down"
        if self.breaker is not None \
                and self.breaker.effective_state() == "open":
            return "open"
        if self._state == DOWN:
            return "down"
        if self._state in (DRAINING, RELOADING) or self.degraded():
            return "degraded"
        return "ok"

    def snapshot(self) -> Dict[str, Any]:
        """Per-replica /statz row: identity + routing signals + the full
        single-engine stats snapshot nested under ``stats``."""
        out = {
            "replica": self.idx,
            "engine_instance": self.engine.stats.instance,
            "state": self._state,
            "status": self.health(),
            "version": self.version,
            "weights_round": self.engine.weights_round,
            "weights_digest": self.engine.weights_digest,
            "queued_rows": self.batcher.queued_rows,
            "queue_frac": round(self.queue_frac(), 4),
            "devices": self.engine.trainer.mesh.num_devices,
            "stats": self.engine.stats.snapshot(),
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out

    def close(self, drain: bool = True) -> None:
        self.set_state(DOWN)
        # LM plane first: its scheduler holds KV blocks the batcher's
        # idle probe watches, so draining it unblocks the batcher drain
        if self.lm is not None:
            self.lm.stop(drain=drain)
            self.lm.engine.close()
        self.batcher.close(drain=drain)
        self.engine.stats.unregister()
        fam = REGISTRY.get("cxxnet_serve_replica_state")
        if fam is not None:
            fam.remove_labels(self.engine.stats.instance)


class ReplicaPool:
    """N replicas + the router. Build with :meth:`build` (device-slice
    partitioning) or pass pre-built replicas directly (tests)."""

    def __init__(self, replicas: Sequence[Replica],
                 admission_control: bool = True):
        if not replicas:
            raise ValueError("replica pool needs at least one replica")
        self.replicas: List[Replica] = list(replicas)
        self.admission_control = bool(admission_control)
        self.instance = str(next(_POOL_SEQ))
        self._lock = threading.Lock()
        self._rr = 0
        # per-version terminal-outcome accounting (the A/B comparison
        # readout): version -> {requests, ok, failed, lat_sum}
        self._vstats: Dict[str, Dict[str, float]] = {}
        # recent failed-request trace ids per version: the evidence a
        # deploy_incident carries so a rolled-back canary's failures
        # are findable in the assembled fleet trace (bounded; only
        # sampled traces land here)
        self._failed_traces: Dict[str, deque] = {}
        self._c_version = REGISTRY.counter(
            "cxxnet_serve_version_requests_total",
            "Pool requests by model version and outcome",
            labels=("pool", "version", "result"))

    # -- construction ----------------------------------------------------
    @classmethod
    def build(cls, cfg: Any, n_replicas: int, *,
              blob: Optional[Dict[str, Any]] = None,
              digest: str = "",
              devices: Optional[Sequence] = None,
              admission_control: bool = True,
              buckets: Any = None, max_batch: int = 64,
              cache_size: int = 16, dtype: Optional[str] = None,
              max_latency_ms: float = 5.0, max_queue_rows: int = 1024,
              default_timeout_ms: Optional[float] = None,
              breaker_threshold: int = 5, breaker_reset_s: float = 10.0,
              degraded_queue_frac: float = 0.8,
              slo_ms: float = 0.0, slo_target: float = 0.99,
              slo_window_s: float = 60.0,
              slo_burn_degraded: float = 2.0,
              silent: bool = False) -> "ReplicaPool":
        """Build ``n_replicas`` engines over disjoint device slices.

        With >= n devices, each replica gets a contiguous
        ``len(devices) // n`` slice (equal slices, so every replica
        shares one bucket ladder); with fewer devices than replicas,
        replicas share devices round-robin — still useful on CPU, where
        extra replicas overlap host-side batching with device compute
        and give the reload/AB machinery real parallelism to work
        against.

        ``blob`` is an already-verified inference checkpoint blob
        (``checkpoint.load_for_inference`` / ``find_latest_valid``):
        loaded ONCE on the host, placed per replica — N replicas never
        re-read (or re-hash) the archive N times. Without a blob the
        replicas serve freshly initialized weights (smoke mode, same
        contract as the single-engine path).
        """
        import jax
        from ..config import parse_config_string
        from ..parallel import make_mesh_context
        from ..trainer import Trainer
        from .engine import negotiate_blob, restore_inference_blob

        if blob is not None:
            # dtype negotiation ONCE for the whole fleet (not per
            # replica): int8 engines demand a PTQ-derived round, fp
            # engines dequantize a quantized one on load
            blob = negotiate_blob(blob, dtype)
        n = int(n_replicas)
        if n < 1:
            raise ValueError(f"serve_replicas must be >= 1, got {n}")
        pairs = parse_config_string(cfg) if isinstance(cfg, str) \
            else list(cfg)
        devs = list(devices if devices is not None else jax.devices())
        if len(devs) >= n:
            per = len(devs) // n
            groups = [devs[i * per:(i + 1) * per] for i in range(n)]
            if len(devs) % n and not silent:
                # equal slices keep every replica on one bucket ladder
                # (buckets must divide the per-replica dp degree), so
                # the remainder idles — say so instead of silently
                # serving on a fraction of the fleet
                print(f"serve-fleet: {len(devs) % n} of {len(devs)} "
                      f"devices idle ({n} replicas x {per} devices "
                      "each); pick serve_replicas dividing the device "
                      "count to use them all", flush=True)
        else:
            groups = [[devs[i % len(devs)]] for i in range(n)]

        replicas: List[Replica] = []
        version = "init"
        if blob is not None:
            # the quantized artifact is a distinct version: '-int8'
            # suffix keeps pins/tiers from conflating it with the fp
            # source round it derives from
            version = version_name(blob["meta"]["round"]) \
                + ("-int8" if bool(dtype)
                   and str(dtype).lower() == "int8" else "")
        for i, group in enumerate(groups):
            tr = Trainer(pairs, mesh_ctx=make_mesh_context(devices=group))
            if blob is not None:
                restore_inference_blob(tr, blob)
            else:
                tr.init_model()
                # an engine never steps the optimizer; N replicas of
                # momentum buffers would be pure waste
                tr.opt_state = None
            engine = InferenceEngine(
                tr, buckets=buckets, max_batch=max_batch,
                cache_size=cache_size, dtype=dtype)
            if blob is not None:
                engine.weights_digest = digest
                engine.weights_version = version
            breaker = (CircuitBreaker(failure_threshold=breaker_threshold,
                                      reset_timeout_s=breaker_reset_s)
                       if breaker_threshold > 0 else None)
            slo = None
            if slo_ms > 0:
                slo = SLOTracker(slo_ms, target=slo_target,
                                 window_s=slo_window_s,
                                 instance=engine.stats.instance)
                engine.stats.slo = slo
            batcher = MicroBatcher(
                engine, max_latency_ms=max_latency_ms,
                max_queue_rows=max_queue_rows,
                default_timeout_ms=default_timeout_ms,
                breaker=breaker)
            replicas.append(Replica(
                i, engine, batcher, breaker, slo,
                degraded_queue_frac=degraded_queue_frac,
                slo_burn_degraded=slo_burn_degraded))
        return cls(replicas, admission_control=admission_control)

    # -- routing ---------------------------------------------------------
    def versions(self) -> Dict[str, List[int]]:
        """version -> replica indices currently serving it."""
        out: Dict[str, List[int]] = {}
        for r in self.replicas:
            out.setdefault(r.version, []).append(r.idx)
        return out

    def pick(self, version: Optional[str] = None) -> Replica:
        """Route one request (see module docstring for the policy)."""
        cands = [r for r in self.replicas
                 if version is None or r.version == version]
        if version is not None and not cands:
            raise UnknownVersion(
                f"no replica serves model version {version!r}; "
                f"available: {sorted(self.versions())}")
        avail = [r for r in cands if r.available()]
        if not avail:
            raise NoHealthyReplica(
                "no replica available"
                + (f" for version {version!r}" if version else "")
                + ": all down, draining, or breaker-open — retry later")
        healthy = [r for r in avail if not r.degraded()]
        if not healthy:
            if self.admission_control:
                raise AllReplicasDegraded(
                    "admission control: every available replica is "
                    "degraded (SLO burn / queue saturation) — "
                    "shedding load, retry later")
            healthy = avail
        with self._lock:
            self._rr += 1
            rr = self._rr
        n = len(self.replicas)
        # least queued rows; round-robin rotation breaks ties
        return min(healthy, key=lambda r: (r.batcher.queued_rows,
                                           (r.idx - rr) % n))

    def submit(self, data, kind: str = "predict",
               node: Optional[str] = None,
               timeout_ms: Optional[float] = None,
               version: Optional[str] = None):
        """Route + enqueue one request; returns the result Future. The
        pick is re-validated under the replica's admission lock before
        the enqueue: a reload flipping the replica to DRAINING (or
        swapping its version) between pick() and submit would otherwise
        serve a version-pinned request from the wrong model. The
        per-version outcome accounting hangs off the future so A/B
        comparisons see terminal results, not admissions.

        With distributed tracing on, the route decision lands as a
        ``serve.route`` child span on the request's trace naming the
        replica that won — the assembled fleet trace answers "which
        replica served this slow request" without cross-referencing
        stats. One attribute check (``current`` is None) when off."""
        route_ctx = DISTTRACE.current()
        t_route0 = time.perf_counter() if route_ctx is not None else 0.0
        t_route1 = t_route0
        for _ in range(8):            # re-pick bound: reloads are rare
            rep = self.pick(version)
            with rep.admission_lock:
                if rep.state != UP or (version is not None
                                       and rep.version != version):
                    continue          # lost a race with a reload
                ver = rep.version
                # route ends BEFORE the enqueue: queue_wait starts
                # inside submit(), and the critical-path report sums
                # the two as disjoint segments of the request e2e
                if route_ctx is not None:
                    t_route1 = time.perf_counter()
                fut = rep.batcher.submit(data, kind, node,
                                         timeout_ms=timeout_ms)
                break
        else:
            raise NoHealthyReplica(
                "could not admit request: replicas kept transitioning "
                "(reload storm?) — retry later")
        if route_ctx is not None:
            DISTTRACE.record("serve.route", t_route0, t_route1,
                             route_ctx, cat="serve",
                             args={"replica": rep.idx, "version": ver})
        t0 = time.perf_counter()
        with self._lock:
            vs = self._vstats.setdefault(
                ver, {"requests": 0, "ok": 0, "failed": 0, "lat_sum": 0.0})
            vs["requests"] += 1

        def _done(f):
            ok = f.exception() is None
            with self._lock:
                vs["ok" if ok else "failed"] += 1
                if ok:
                    vs["lat_sum"] += time.perf_counter() - t0
                elif route_ctx is not None and route_ctx.sampled:
                    # keep the failure's trace id: a deploy incident
                    # names the requests that condemned the canary
                    self._failed_traces.setdefault(
                        ver, deque(maxlen=16)).append(route_ctx.trace_id)
            self._c_version.labels(self.instance, ver,
                                   "ok" if ok else "failed").inc()
        fut.add_done_callback(_done)
        return fut

    # -- LM serving plane (serve/lm) --------------------------------------
    def attach_lm(self, lm_cfg) -> None:
        """Bring up the LM serving plane: one paged-KV LMEngine +
        continuous-batching scheduler per replica, sharing the
        replica's weights / mesh / hot-reload machinery. The scheduler
        registers as a batcher idle probe (a drain waits for decode
        sequences still holding KV blocks, not just batch rows) and as
        the stats ``lm`` hook (/statz shows rows + KV occupancy)."""
        from .lm import LMEngine, LMScheduler
        for rep in self.replicas:
            if rep.lm is not None:
                raise RuntimeError(
                    f"replica {rep.idx} already has an LM plane")
            lme = LMEngine(rep.engine, lm_cfg)
            sched = LMScheduler(lme, lm_cfg)
            sched.start()
            rep.batcher.add_idle_probe(sched.live_count)
            rep.engine.stats.lm = sched.snapshot
            rep.lm = sched

    def set_lm_role(self, idx: int, role: str, peer=None) -> None:
        """Flip one replica's plane mid-run — e.g. disaggregate by
        pointing replica 0 at replica 1's handoff listener:
        ``pool.set_lm_role(0, "prefill",
        peer=pool.replicas[1].lm.handoff_addr)``."""
        rep = self.replicas[int(idx)]
        if rep.lm is None:
            raise RuntimeError(f"replica {idx} has no LM plane")
        rep.lm.set_role(role, peer)

    def submit_lm(self, prompt, max_new: Optional[int] = None,
                  deadline_ms: Optional[float] = None,
                  version: Optional[str] = None):
        """Route one generation request; returns its StreamHandle.
        Same pick discipline as :meth:`submit` (availability, version
        pin, admission re-check under the replica lock) over the
        replicas that can START a sequence — decode-role replicas only
        take prefill handoffs, so the router skips them."""
        cands = [r for r in self.replicas
                 if r.lm is not None and r.lm.role != "decode"
                 and (version is None or r.version == version)]
        if version is not None and not cands:
            raise UnknownVersion(
                f"no replica serves model version {version!r}; "
                f"available: {sorted(self.versions())}")
        if not cands:
            raise NoHealthyReplica(
                "no replica accepts LM requests (none attached, or all "
                "decode-role)")
        for _ in range(8):            # re-pick bound, as in submit()
            avail = [r for r in cands if r.available()]
            if not avail:
                raise NoHealthyReplica(
                    "no LM replica available: all down, draining, or "
                    "breaker-open — retry later")
            rep = min(avail, key=lambda r: r.lm.live_count())
            with rep.admission_lock:
                if rep.state != UP or (version is not None
                                       and rep.version != version):
                    continue          # lost a race with a reload
                return rep.lm.submit(prompt, max_new=max_new,
                                     deadline_ms=deadline_ms)
        raise NoHealthyReplica(
            "could not admit LM request: replicas kept transitioning "
            "(reload storm?) — retry later")

    def failed_traces(self, version: str) -> List[str]:
        """Trace ids of recent failed requests against ``version``
        (newest last; empty when tracing is off/unsampled)."""
        with self._lock:
            return list(self._failed_traces.get(version, ()))

    # -- reload hooks (serve/reload.py drives these) ---------------------
    def reload_replica(self, idx: int, params, net_state,
                       round_counter: int, digest: str = "",
                       drain_timeout_s: float = 30.0) -> int:
        """Swap one replica's weights with graceful drain: DRAINING
        takes it out of rotation (its admitted work still completes),
        the swap happens only once the batcher is quiescent, and the
        replica returns UP — zero dropped requests. On drain timeout the
        swap proceeds anyway (the engine's weights lock keeps any
        straggling dispatch consistent). Returns the OLD round."""
        rep = self.replicas[int(idx)]
        old_round = rep.engine.weights_round
        # DRAINING flips under the admission lock: after this, no
        # already-picked request can still be admitted (fleet.submit
        # re-checks state under the same lock), so batcher.idle really
        # does mean quiescent
        with rep.admission_lock:
            rep.set_state(DRAINING)
        try:
            deadline = time.perf_counter() + drain_timeout_s
            while not rep.batcher.idle \
                    and time.perf_counter() < deadline:
                time.sleep(0.002)
            rep.set_state(RELOADING)
            rep.engine.swap_weights(params, net_state, round_counter,
                                    digest)
        finally:
            rep.set_state(UP)
        return old_round

    def newest_round(self) -> int:
        """Newest checkpoint round any replica serves (-1 when every
        replica still serves init weights) — the reload watcher's
        "is this checkpoint new" reference point."""
        rounds = [r.engine.weights_round for r in self.replicas
                  if r.version != "init"]
        return max(rounds) if rounds else -1

    # -- aggregate views -------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Fleet health: the WORST replica decides the top-level status
        (conservative by design — a fleet hiding a sick replica behind
        an 'ok' is how slow-burn incidents stay invisible); per-replica
        statuses ride along so operators see which one."""
        rank = {"ok": 0, "degraded": 1, "open": 2, "down": 3}
        statuses = [r.health() for r in self.replicas]
        worst = max(statuses, key=lambda s: rank[s])
        return {
            "status": worst,
            "replicas": [
                {"replica": r.idx, "status": s, "state": r.state,
                 "version": r.version,
                 "queued_rows": r.batcher.queued_rows,
                 "burn_rate": round(r.burn_rate(), 4)}
                for r, s in zip(self.replicas, statuses)],
            "versions": self.versions(),
        }

    def version_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-version outcome stats. Currently-served versions always
        appear (a freshly rolled canary with zero traffic yet must show
        up in /statz); retired versions keep their numbers for the A/B
        comparison."""
        serving = self.versions()
        with self._lock:
            out = {}
            for ver in set(serving) | set(self._vstats):
                vs = self._vstats.get(
                    ver, {"requests": 0, "ok": 0, "failed": 0,
                          "lat_sum": 0.0})
                done = vs["ok"]
                out[ver] = {
                    "replicas": serving.get(ver, []),
                    "requests": int(vs["requests"]),
                    "ok": int(vs["ok"]),
                    "failed": int(vs["failed"]),
                    "mean_ms": round(1e3 * vs["lat_sum"] / done, 3)
                    if done else 0.0,
                }
            return out

    def snapshot(self) -> Dict[str, Any]:
        """Aggregate /statz payload: the single-engine key layout at the
        top level (summed across replicas, percentiles over the pooled
        latency reservoirs) so PR-1 clients and dashboards keep working,
        plus ``replicas`` / ``versions`` breakdowns."""
        stats = [r.engine.stats for r in self.replicas]
        lat = sorted(s for st in stats for s in st.latency_samples())
        pct = ServingStats._pct
        uptime = max(st.snapshot_uptime() for st in stats)
        rows_real = sum(st.rows_real for st in stats)
        rows_padded = sum(st.rows_padded for st in stats)
        b_disp = sum(st.batches_dispatched for st in stats)
        req_batched = sum(st.requests_batched for st in stats)
        out = {
            "uptime_s": round(uptime, 3),
            "requests": {
                "total": sum(st.requests_total for st in stats),
                "ok": sum(st.requests_ok for st in stats),
                "rejected_backpressure":
                    sum(st.rejected_backpressure for st in stats),
                "rejected_deadline":
                    sum(st.rejected_deadline for st in stats),
                "rejected_breaker":
                    sum(st.rejected_breaker for st in stats),
                "failed": sum(st.failed for st in stats),
            },
            "qps": round(sum(st.recent_qps() for st in stats), 3),
            "latency_ms": {
                "p50": round(1e3 * pct(lat, 0.50), 3),
                "p95": round(1e3 * pct(lat, 0.95), 3),
                "p99": round(1e3 * pct(lat, 0.99), 3),
                "mean": round(1e3 * sum(lat) / len(lat), 3) if lat
                        else 0.0,
                "samples": len(lat),
            },
            "batches": {
                "dispatched": b_disp,
                "coalesced_ge2":
                    sum(st.batches_coalesced_ge2 for st in stats),
                "avg_requests_per_batch":
                    round(req_batched / b_disp, 3) if b_disp else 0.0,
                "fill_ratio": round(rows_real / rows_padded, 4)
                if rows_padded else 0.0,
                "rows_real": rows_real,
                "rows_padded": rows_padded,
            },
            "compile_cache": {
                "hits": sum(st.cache_hits for st in stats),
                "misses": sum(st.cache_misses for st in stats),
                "evictions": sum(st.cache_evictions for st in stats),
                "size": sum(st.cache_size for st in stats),
                "capacity": sum(st.cache_capacity for st in stats),
            },
            "replicas": [r.snapshot() for r in self.replicas],
            "versions": self.version_stats(),
        }
        return out

    # -- lifecycle -------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        for r in self.replicas:
            r.close(drain=drain)
        fam = REGISTRY.get("cxxnet_serve_version_requests_total")
        if fam is not None:
            with self._lock:
                for ver in self._vstats:
                    for res in ("ok", "failed"):
                        fam.remove_labels(self.instance, ver, res)
