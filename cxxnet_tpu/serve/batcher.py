"""Dynamic micro-batching queue for the inference engine.

Requests accumulate until ``max_batch`` rows or ``max_latency_ms``
elapses, then dispatch as ONE device call (engine.run_padded) and the
results scatter back to per-request futures. Pure-Python threading —
the same worker/bounded-queue pattern as the IO pipeline's
ThreadBufferIterator (io/proc.py) — with:

* **backpressure**: a bounded row budget; ``submit`` raises
  :class:`Backpressure` instead of queueing unboundedly;
* **deadlines**: each request may carry ``timeout_ms``; requests whose
  deadline passed by dispatch time are rejected with
  :class:`DeadlineExceeded` rather than served stale;
* **circuit breaking**: an optional :class:`resilience.CircuitBreaker`
  — N consecutive dispatch failures (a wedged/poisoned device) flip it
  open and ``submit`` fails fast with :class:`CircuitOpen` (HTTP 503)
  instead of letting every client wait out the full batching window
  just to collect a 500; after the reset timeout one half-open probe
  request is admitted and its outcome closes or re-opens the breaker.

Requests of different output kinds (predict / raw / extract[node])
cannot share a device call, so pending work is grouped per
``(kind, node)`` and each group flushes independently.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..resilience import CircuitBreaker, CircuitOpen
from ..telemetry.disttrace import DISTTRACE
from ..telemetry.registry import REGISTRY
from ..telemetry.trace import TRACER
from .engine import InferenceEngine
from .stats import ServingStats


class Backpressure(RuntimeError):
    """Queue row budget exhausted; retry later (HTTP 503)."""


class DeadlineExceeded(TimeoutError):
    """Request expired before its batch dispatched (HTTP 504)."""


class _Request:
    __slots__ = ("rows", "kind", "node", "future", "t_submit", "deadline",
                 "ctx")

    def __init__(self, rows, kind, node, deadline):
        self.rows = rows
        self.kind = kind
        self.node = node
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline          # perf_counter abs, or None
        # distributed-trace context of the submitting thread (the HTTP
        # handler's serve.request span): the worker attributes this
        # request's queue-wait / batch-assembly / infer segments to it
        # across the thread hop. None (one attr check) when tracing off.
        self.ctx = DISTTRACE.current()


class MicroBatcher:
    def __init__(self, engine: InferenceEngine,
                 max_batch: Optional[int] = None,
                 max_latency_ms: float = 5.0,
                 max_queue_rows: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 stats: Optional[ServingStats] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.engine = engine
        self.stats = stats or engine.stats
        self.breaker = breaker
        # clamped to the engine's largest bucket: a dispatch bigger than
        # the bucket ceiling could never run as one device call
        self.max_batch = min(int(max_batch or engine.max_batch),
                             engine.max_batch)
        self.max_latency_s = max_latency_ms / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.default_timeout_ms = default_timeout_ms
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._rows_lock = threading.Lock()
        self._queued_rows = 0
        self._dispatching = 0           # flushes currently past _release
        # external live-work probes (serve/lm scheduler): callables
        # returning a count of in-flight items OUTSIDE the row
        # accounting — decode sequences still holding KV blocks. `idle`
        # consults them so hot reload / deploy gating never swaps
        # weights under a half-generated stream.
        self._live_probes: List = []
        # admitted-but-undispatched rows, straight off the backpressure
        # accounting (labeled like the ServingStats serve metrics;
        # close() drops the series again)
        self._g_depth_fam = REGISTRY.gauge(
            "cxxnet_serve_queue_rows",
            "Rows admitted to the micro-batcher but not yet dispatched",
            labels=("engine",))
        self._g_depth = self._g_depth_fam.labels(self.stats.instance)
        self._stop = threading.Event()
        self._drain = True
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # -- client side -----------------------------------------------------
    def submit(self, data, kind: str = "predict",
               node: Optional[str] = None,
               timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the result
        rows for this request (np.ndarray). Raises Backpressure when the
        row budget is full."""
        rows = self.engine._to_input(data)
        if rows.shape[0] == 0:
            raise ValueError("empty request")
        if rows.shape[0] > self.max_batch:
            raise ValueError(
                f"request rows {rows.shape[0]} > max_batch "
                f"{self.max_batch}; split client-side or call the engine "
                "directly")
        self.stats.record_request()
        # breaker gate AFTER input validation (malformed requests are the
        # client's fault, not the device's) and BEFORE queueing: an open
        # breaker must answer in microseconds, not a batching window
        if self.breaker is not None and not self.breaker.allow():
            self.stats.record_reject("breaker")
            raise CircuitOpen(
                f"serve circuit breaker open ({self.breaker.state}); "
                "device dispatches are failing — retry later")
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = (time.perf_counter() + timeout_ms / 1e3
                    if timeout_ms else None)
        req = _Request(rows, kind, node, deadline)
        # stop-check + put under the SAME lock close() sets _stop under:
        # otherwise a submit could pass the check, get preempted, and put
        # after the worker's final drain — a future no one ever resolves
        with self._rows_lock:
            if self._stop.is_set():
                raise RuntimeError("batcher is shut down")
            if self._queued_rows + rows.shape[0] > self.max_queue_rows:
                self.stats.record_reject("backpressure")
                raise Backpressure(
                    f"serve queue full ({self._queued_rows} rows "
                    f">= {self.max_queue_rows})")
            self._queued_rows += rows.shape[0]
            self._g_depth.set(self._queued_rows)
            self._q.put(req)
        return req.future

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0
              ) -> None:
        """Stop the worker. ``drain=True`` serves everything already
        queued first (graceful shutdown); ``drain=False`` rejects it."""
        self._drain = drain
        with self._rows_lock:             # see submit(): no put after stop
            self._stop.set()
        self._q.put(None)                 # wake the worker
        self._thread.join(timeout=timeout)
        self._g_depth_fam.remove_labels(self.stats.instance)

    @property
    def queued_rows(self) -> int:
        """Rows currently admitted but not yet dispatched (the /healthz
        queue-saturation signal)."""
        with self._rows_lock:
            return self._queued_rows

    def add_idle_probe(self, probe) -> None:
        """Register a live-work probe (a callable returning an int count
        of in-flight items) that must read 0 before ``idle`` is True.
        The LM scheduler registers its live-sequence count here: a
        drained micro-batcher with decodes still holding KV blocks is
        NOT idle — reload/deploy gating reads ``idle`` to decide when a
        weight swap is safe, and swapping mid-stream would hand a
        sequence logits from a model that never saw its prefix."""
        with self._rows_lock:
            self._live_probes.append(probe)

    @property
    def idle(self) -> bool:
        """True when nothing is admitted AND no flush is mid-dispatch
        AND every registered live-work probe reads 0 — the quiesce
        condition a hot weight reload drains to. queued_rows alone is
        not enough: _flush releases the row accounting BEFORE the
        device call, so a reload keyed on it could swap weights under
        an in-flight dispatch; and LM decode sequences hold KV state
        across many device calls with zero queued rows in between."""
        with self._rows_lock:
            probes = list(self._live_probes)
            if self._queued_rows != 0 or self._dispatching != 0:
                return False
        return all(int(p()) == 0 for p in probes)

    # -- worker side -----------------------------------------------------
    def _release(self, reqs: List[_Request]) -> None:
        n = sum(r.rows.shape[0] for r in reqs)
        with self._rows_lock:
            self._queued_rows -= n
            self._g_depth.set(self._queued_rows)

    def _flush(self, reqs: List[_Request]) -> None:
        """Reject expired requests, then dispatch the group in chunks of
        at most ``max_batch`` rows (a group can overshoot when the append
        that crossed the threshold was multi-row, and the drain path
        flushes arbitrary backlogs)."""
        with self._rows_lock:
            # keeps `idle` False across the _release -> dispatch gap
            self._dispatching += 1
        try:
            self._release(reqs)
            now = time.perf_counter()
            live: List[_Request] = []
            for r in reqs:
                if r.deadline is not None and now > r.deadline:
                    self.stats.record_reject("deadline")
                    r.future.set_exception(DeadlineExceeded(
                        "request expired before dispatch"))
                else:
                    live.append(r)
            chunk: List[_Request] = []
            n_rows = 0
            for r in live:
                if chunk and n_rows + r.rows.shape[0] > self.max_batch:
                    self._dispatch(chunk)
                    chunk, n_rows = [], 0
                chunk.append(r)
                n_rows += r.rows.shape[0]
            if chunk:
                self._dispatch(chunk)
        finally:
            with self._rows_lock:
                self._dispatching -= 1

    def _dispatch(self, live: List[_Request]) -> None:
        """ONE device call for one chunk; scatter results to futures."""
        # queue-wait: earliest member submit -> now, recorded with
        # explicit begin/end so it lands on the worker's trace track
        t_now = time.perf_counter()
        TRACER.add_complete("serve.queue_wait",
                            min(r.t_submit for r in live), t_now,
                            cat="serve", args={"requests": len(live)})
        with TRACER.span("serve.batch_assembly", cat="serve",
                         args={"requests": len(live)}):
            rows = (live[0].rows if len(live) == 1
                    else np.concatenate([r.rows for r in live], axis=0))
        t_asm1 = time.perf_counter()
        try:
            out = self.engine.run_padded(rows, live[0].kind, live[0].node)
        except Exception as e:
            if self.breaker is not None:
                self.breaker.record_failure()
            for r in live:
                self.stats.record_failure()
                r.future.set_exception(e)
            return
        t_infer1 = time.perf_counter()
        if self.breaker is not None:
            self.breaker.record_success()
        if DISTTRACE.enabled:
            # per-request critical-path attribution, parented across the
            # thread hop onto each request's serve.request span: queue
            # wait (submit -> dispatch), batch assembly, infer. A batch
            # shares the assembly/infer wall time — each member sees the
            # full segment, which is exactly what its request paid.
            for r in live:
                if r.ctx is not None:
                    DISTTRACE.record("serve.queue_wait", r.t_submit,
                                     t_now, r.ctx, cat="serve",
                                     args={"requests": len(live)})
                    DISTTRACE.record("serve.batch_assembly", t_now,
                                     t_asm1, r.ctx, cat="serve",
                                     args={"requests": len(live)})
                    DISTTRACE.record("serve.infer", t_asm1, t_infer1,
                                     r.ctx, cat="serve",
                                     args={"rows": int(rows.shape[0])})
        self.stats.record_batch(
            n_requests=len(live), rows_real=rows.shape[0],
            rows_bucket=self.engine.bucket_for(rows.shape[0]))
        off = 0
        t_done = time.perf_counter()
        for r in live:
            n = r.rows.shape[0]
            r.future.set_result(out[off:off + n])
            self.stats.record_done(t_done - r.t_submit)
            off += n

    def _worker(self) -> None:
        # pending groups: (kind, node) -> (first_arrival_t, [requests])
        pending: Dict[Tuple[str, Optional[str]],
                      Tuple[float, List[_Request]]] = {}

        def group_rows(reqs: List[_Request]) -> int:
            return sum(r.rows.shape[0] for r in reqs)

        def group_due(t0: float, reqs: List[_Request]) -> float:
            """When this group must dispatch: the latency window end, or
            earlier when a member's deadline would expire first — a
            timeout_ms shorter than max_latency_ms must be SERVED on an
            idle queue, not auto-rejected at the window. The flush is
            scheduled 50 ms ahead of the deadline: queue.get wakeups
            routinely slip several ms past their timeout on a loaded
            host, and a margin smaller than that slip turns every
            deadline-driven flush into a rejection race."""
            due = t0 + self.max_latency_s
            dls = [r.deadline for r in reqs if r.deadline is not None]
            if dls:
                due = min(due, min(dls) - 0.05)
            return due

        def flush_due(force: bool = False) -> None:
            now = time.perf_counter()
            for key in list(pending):
                t0, reqs = pending[key]
                if force or now >= group_due(t0, reqs) \
                        or group_rows(reqs) >= self.max_batch:
                    del pending[key]
                    self._flush(reqs)

        while True:
            stopping = self._stop.is_set()
            if pending:
                t_next = min(group_due(t0, reqs)
                             for t0, reqs in pending.values())
                wait = max(0.0, t_next - time.perf_counter())
            else:
                wait = 0.1
            try:
                # once stopping, drain whatever is already queued without
                # waiting — a flush may have consumed 0.4s+ while close()
                # landed, leaving a tail of accepted requests behind the
                # sentinel AND after it
                req = self._q.get_nowait() if stopping \
                    else self._q.get(timeout=wait)
            except queue.Empty:
                if stopping:                  # queue fully drained
                    if self._drain:
                        flush_due(force=True)
                    else:
                        err = RuntimeError("batcher shut down")
                        for _t0, reqs in pending.values():
                            self._release(reqs)
                            for r in reqs:
                                r.future.set_exception(err)
                        pending.clear()
                    break
                flush_due()
                continue
            if req is None:                   # shutdown sentinel
                continue                      # keep draining until Empty
            if stopping and not self._drain:
                self._release([req])
                req.future.set_exception(RuntimeError("batcher shut down"))
                continue
            key = (req.kind, req.node)
            t0, reqs = pending.get(key, (time.perf_counter(), []))
            reqs.append(req)
            pending[key] = (t0, reqs)
            if group_rows(reqs) >= self.max_batch:
                del pending[key]
                self._flush(reqs)
            else:
                flush_due()
        # post-loop: nothing pending survives (flushed or rejected above)
