"""Stdlib JSON front-end for the inference engine.

``http.server``-based so the engine is drivable end-to-end with zero new
dependencies (the same reason the IO pipeline is pure stdlib threading):

* ``POST /predict``  ``{"data": [[...], ...], "raw": 0|1,
  "timeout_ms": N?}`` -> ``{"pred": [...]}`` / ``{"prob": [[...]]}``
* ``POST /extract``  ``{"data": ..., "node": "name"}``
  -> ``{"features": [[...]]}``
* ``GET  /healthz``  -> ``{"status": "ok"|"degraded"|"open"|"down", ...}``
* ``GET  /statz``    -> the ServingStats snapshot + breaker/queue state

Health semantics (what a load balancer keys routing on):

* ``ok``       (200) — dispatching normally;
* ``degraded`` (200) — still serving but impaired: the admitted-row
  queue is past ``degraded_queue_frac`` of its budget, the breaker is
  half-open (probing a recovering device), corrupt input records
  have been skipped this process (``recordio.skipped``), or the
  latency-SLO burn rate is at/over ``slo_burn_degraded`` (the error
  budget is being eaten unsustainably fast) — keep routing, start
  paging;
* ``open``     (503) — the circuit breaker is open: dispatches are
  failing and requests are being rejected fast — route elsewhere;
* ``down``     (500) — the batcher worker is dead.

Error mapping: malformed request 400, backpressure AND breaker-open 503
(retry later), deadline exceeded 504, engine failure 500. Shutdown is
graceful: stop accepting, then drain the batcher so queued requests
still get answers.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from ..resilience import CircuitBreaker, CircuitOpen, counters
from ..telemetry import PROMETHEUS_CONTENT_TYPE, render_prometheus
from ..telemetry.ledger import run_info
from ..telemetry.slo import SLOTracker
from ..telemetry.trace import TRACER
from .batcher import Backpressure, DeadlineExceeded, MicroBatcher
from .engine import InferenceEngine
from .stats import ServingStats


def _make_handler(server: "ServeServer"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):          # quiet per-request spam
            if not server.silent and server.verbose:
                BaseHTTPRequestHandler.log_message(self, fmt, *args)

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code >= 400:
                # error paths may leave the POST body unread; on an
                # HTTP/1.1 keep-alive socket those bytes would be parsed
                # as the next request line — drop the connection instead
                self.close_connection = True
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                code, payload = server.health()
                self._reply(code, payload)
            elif self.path == "/statz":
                self._reply(200, server.statz())
            elif self.path == "/metrics":
                # one scrape = the WHOLE process registry: serve,
                # resilience, checkpoint, io — not just this server's
                body = render_prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no such path {self.path}"})

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            if n <= 0 or n > server.max_body_bytes:
                raise ValueError(f"bad Content-Length {n}")
            return json.loads(self.rfile.read(n).decode("utf-8"))

        def do_POST(self):
            if self.path not in ("/predict", "/extract"):
                self._reply(404, {"error": f"no such path {self.path}"})
                return
            # full request-lifecycle span (parse -> queue -> infer ->
            # respond nest inside it on this handler thread's track)
            with TRACER.span("serve.request", cat="serve",
                             args={"path": self.path}):
                self._handle_post()

        def _handle_post(self):
            try:
                req = self._read_json()
                data = np.asarray(req["data"], np.float32)
                if data.ndim == 1:       # single instance shorthand
                    data = data[None, :]
                timeout_ms = req.get("timeout_ms")
                # hard cap so a wedged worker can't hang handler threads
                # forever (batcher deadlines are the soft mechanism)
                if self.path == "/extract":
                    node = req.get("node", "top")
                    fut = server.batcher.submit(data, "extract", node,
                                                timeout_ms=timeout_ms)
                    out = fut.result(timeout=server.result_timeout_s)
                    with TRACER.span("serve.respond", cat="serve"):
                        self._reply(200, {"node": node,
                                          "features": out.tolist()})
                else:
                    kind = "raw" if int(req.get("raw", 0)) else "predict"
                    fut = server.batcher.submit(data, kind,
                                                timeout_ms=timeout_ms)
                    out = fut.result(timeout=server.result_timeout_s)
                    key = "prob" if kind == "raw" else "pred"
                    with TRACER.span("serve.respond", cat="serve"):
                        self._reply(200, {key: out.tolist()})
            except (Backpressure, CircuitOpen) as e:
                self._reply(503, {"error": str(e)})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


class ServeServer:
    """Engine + batcher + HTTP front-end, with a periodic stats log line
    (the serving analog of the trainer's round metric line)."""

    def __init__(self, engine: InferenceEngine,
                 port: int = 0, host: str = "127.0.0.1",
                 max_batch: Optional[int] = None,
                 max_latency_ms: float = 5.0,
                 max_queue_rows: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 log_interval_s: float = 30.0,
                 silent: bool = False, verbose: bool = False,
                 max_body_bytes: int = 64 << 20,
                 result_timeout_s: float = 120.0,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 10.0,
                 degraded_queue_frac: float = 0.8,
                 slo_ms: float = 0.0,
                 slo_target: float = 0.99,
                 slo_window_s: float = 60.0,
                 slo_burn_degraded: float = 2.0):
        self.engine = engine
        self.stats: ServingStats = engine.stats
        self.silent = silent
        self.verbose = verbose
        self.max_body_bytes = max_body_bytes
        self.result_timeout_s = result_timeout_s
        self.log_interval_s = log_interval_s
        self.degraded_queue_frac = float(degraded_queue_frac)
        # latency SLO: every terminal outcome (ok/over-latency/reject/
        # failure) is classified good/bad; the rolling burn rate feeds
        # /healthz BELOW — degradation fires while the breaker is still
        # closed, which is what makes it an admission-control signal
        # rather than a post-mortem
        self.slo_burn_degraded = float(slo_burn_degraded)
        self.slo: Optional[SLOTracker] = None
        if slo_ms > 0:
            self.slo = SLOTracker(slo_ms, target=slo_target,
                                  window_s=slo_window_s,
                                  instance=self.stats.instance)
            self.stats.slo = self.slo
        # breaker_threshold = 0 disables circuit breaking entirely
        self.breaker = (CircuitBreaker(failure_threshold=breaker_threshold,
                                       reset_timeout_s=breaker_reset_s)
                        if breaker_threshold > 0 else None)
        # degradation is reported relative to THIS server's lifetime —
        # corrupt records skipped before serving started (e.g. during
        # training in the same process) are not this endpoint's problem
        self._skipped_base = counters.get("recordio.skipped")
        self.batcher = MicroBatcher(
            engine, max_batch=max_batch, max_latency_ms=max_latency_ms,
            max_queue_rows=max_queue_rows,
            default_timeout_ms=default_timeout_ms, stats=self.stats,
            breaker=self.breaker)
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None
        self._log_stop = threading.Event()
        self._log_thread: Optional[threading.Thread] = None

    # -- health ----------------------------------------------------------
    def health(self) -> Tuple[int, Dict]:
        """``ok | degraded | open | down`` + the signals behind the call
        (see module docstring for the load-balancer semantics)."""
        alive = self.batcher is not None \
            and self.batcher._thread.is_alive()
        queued = self.batcher.queued_rows if alive else 0
        queue_frac = queued / max(1, self.batcher.max_queue_rows)
        skipped = counters.get("recordio.skipped") - self._skipped_base
        # effective_state: an open breaker past its reset timeout reads
        # half_open (-> degraded, 200), so a load balancer that drained
        # this node on 503 resumes the trickle of traffic the recovery
        # probe needs — raw "open" would hold it out of rotation forever
        breaker_state = (self.breaker.effective_state()
                         if self.breaker is not None else "disabled")
        burn = self.slo.burn_rate() if self.slo is not None else 0.0
        if not alive:
            status, code = "down", 500
        elif breaker_state == "open":
            status, code = "open", 503
        elif (breaker_state == "half_open"
              or queue_frac >= self.degraded_queue_frac
              or skipped > 0
              or burn >= self.slo_burn_degraded):
            status, code = "degraded", 200
        else:
            status, code = "ok", 200
        out = {
            "status": status,
            "ok": status == "ok",           # back-compat boolean
            "breaker": breaker_state,
            "queued_rows": queued,
            "queue_frac": round(queue_frac, 4),
            "skipped_records": skipped,
        }
        if self.slo is not None:
            out["slo_burn_rate"] = round(burn, 4)
        return code, out

    def statz(self) -> Dict:
        """ServingStats snapshot + the resilience state alongside it."""
        out = self.stats.snapshot()
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        out["queue"] = {"rows": self.batcher.queued_rows,
                        "max_rows": self.batcher.max_queue_rows}
        out["counters"] = counters.snapshot()
        # run identity: joins this process's scraped/statz numbers with
        # the run ledger and the training task's series (same run_id)
        out["run"] = run_info()
        return out

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServeServer":
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()
        if self.log_interval_s > 0 and not self.silent:
            self._log_thread = threading.Thread(
                target=self._log_loop, daemon=True, name="serve-statlog")
            self._log_thread.start()
        if not self.silent:
            print(f"serving on http://{self.httpd.server_address[0]}:"
                  f"{self.port} (/predict /extract /healthz /statz)",
                  flush=True)
        return self

    def _log_loop(self) -> None:
        while not self._log_stop.wait(self.log_interval_s):
            print(self.stats.log_line(), flush=True)

    def stop(self) -> None:
        """Graceful: stop accepting, drain the batcher, then report."""
        self._log_stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10)
        self.batcher.close(drain=True)
        if not self.silent:
            print(self.stats.log_line(), flush=True)
        # drop this engine's per-instance series from the registry —
        # a stopped server's frozen gauges must not be scraped forever
        self.stats.unregister()

    def serve_until_interrupt(self) -> None:
        """Foreground loop for ``task = serve``: block until SIGINT/
        SIGTERM, then shut down gracefully."""
        import signal
        stop = threading.Event()

        def _sig(_signum, _frame):
            stop.set()
        prev_int = signal.signal(signal.SIGINT, _sig)
        prev_term = signal.signal(signal.SIGTERM, _sig)
        try:
            while not stop.wait(0.2):
                pass
        finally:
            signal.signal(signal.SIGINT, prev_int)
            signal.signal(signal.SIGTERM, prev_term)
            self.stop()
