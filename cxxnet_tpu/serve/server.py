"""Stdlib JSON front-end for the inference engine.

``http.server``-based so the engine is drivable end-to-end with zero new
dependencies (the same reason the IO pipeline is pure stdlib threading):

* ``POST /predict``  ``{"data": [[...], ...], "raw": 0|1,
  "timeout_ms": N?}`` -> ``{"pred": [...]}`` / ``{"prob": [[...]]}``
* ``POST /extract``  ``{"data": ..., "node": "name"}``
  -> ``{"features": [[...]]}``
* ``GET  /healthz``  -> ``{"ok": true}``
* ``GET  /statz``    -> the ServingStats snapshot dict

Error mapping: malformed request 400, backpressure 503 (retry later),
deadline exceeded 504, engine failure 500. Shutdown is graceful: stop
accepting, then drain the batcher so queued requests still get answers.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .batcher import Backpressure, DeadlineExceeded, MicroBatcher
from .engine import InferenceEngine
from .stats import ServingStats


def _make_handler(server: "ServeServer"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):          # quiet per-request spam
            if not server.silent and server.verbose:
                BaseHTTPRequestHandler.log_message(self, fmt, *args)

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code >= 400:
                # error paths may leave the POST body unread; on an
                # HTTP/1.1 keep-alive socket those bytes would be parsed
                # as the next request line — drop the connection instead
                self.close_connection = True
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                ok = server.batcher is not None \
                    and server.batcher._thread.is_alive()
                self._reply(200 if ok else 500, {"ok": bool(ok)})
            elif self.path == "/statz":
                self._reply(200, server.stats.snapshot())
            else:
                self._reply(404, {"error": f"no such path {self.path}"})

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            if n <= 0 or n > server.max_body_bytes:
                raise ValueError(f"bad Content-Length {n}")
            return json.loads(self.rfile.read(n).decode("utf-8"))

        def do_POST(self):
            if self.path not in ("/predict", "/extract"):
                self._reply(404, {"error": f"no such path {self.path}"})
                return
            try:
                req = self._read_json()
                data = np.asarray(req["data"], np.float32)
                if data.ndim == 1:       # single instance shorthand
                    data = data[None, :]
                timeout_ms = req.get("timeout_ms")
                # hard cap so a wedged worker can't hang handler threads
                # forever (batcher deadlines are the soft mechanism)
                if self.path == "/extract":
                    node = req.get("node", "top")
                    fut = server.batcher.submit(data, "extract", node,
                                                timeout_ms=timeout_ms)
                    out = fut.result(timeout=server.result_timeout_s)
                    self._reply(200, {"node": node,
                                      "features": out.tolist()})
                else:
                    kind = "raw" if int(req.get("raw", 0)) else "predict"
                    fut = server.batcher.submit(data, kind,
                                                timeout_ms=timeout_ms)
                    out = fut.result(timeout=server.result_timeout_s)
                    key = "prob" if kind == "raw" else "pred"
                    self._reply(200, {key: out.tolist()})
            except Backpressure as e:
                self._reply(503, {"error": str(e)})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


class ServeServer:
    """Engine + batcher + HTTP front-end, with a periodic stats log line
    (the serving analog of the trainer's round metric line)."""

    def __init__(self, engine: InferenceEngine,
                 port: int = 0, host: str = "127.0.0.1",
                 max_batch: Optional[int] = None,
                 max_latency_ms: float = 5.0,
                 max_queue_rows: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 log_interval_s: float = 30.0,
                 silent: bool = False, verbose: bool = False,
                 max_body_bytes: int = 64 << 20,
                 result_timeout_s: float = 120.0):
        self.engine = engine
        self.stats: ServingStats = engine.stats
        self.silent = silent
        self.verbose = verbose
        self.max_body_bytes = max_body_bytes
        self.result_timeout_s = result_timeout_s
        self.log_interval_s = log_interval_s
        self.batcher = MicroBatcher(
            engine, max_batch=max_batch, max_latency_ms=max_latency_ms,
            max_queue_rows=max_queue_rows,
            default_timeout_ms=default_timeout_ms, stats=self.stats)
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None
        self._log_stop = threading.Event()
        self._log_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServeServer":
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()
        if self.log_interval_s > 0 and not self.silent:
            self._log_thread = threading.Thread(
                target=self._log_loop, daemon=True, name="serve-statlog")
            self._log_thread.start()
        if not self.silent:
            print(f"serving on http://{self.httpd.server_address[0]}:"
                  f"{self.port} (/predict /extract /healthz /statz)",
                  flush=True)
        return self

    def _log_loop(self) -> None:
        while not self._log_stop.wait(self.log_interval_s):
            print(self.stats.log_line(), flush=True)

    def stop(self) -> None:
        """Graceful: stop accepting, drain the batcher, then report."""
        self._log_stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10)
        self.batcher.close(drain=True)
        if not self.silent:
            print(self.stats.log_line(), flush=True)

    def serve_until_interrupt(self) -> None:
        """Foreground loop for ``task = serve``: block until SIGINT/
        SIGTERM, then shut down gracefully."""
        import signal
        stop = threading.Event()

        def _sig(_signum, _frame):
            stop.set()
        prev_int = signal.signal(signal.SIGINT, _sig)
        prev_term = signal.signal(signal.SIGTERM, _sig)
        try:
            while not stop.wait(0.2):
                pass
        finally:
            signal.signal(signal.SIGINT, prev_int)
            signal.signal(signal.SIGTERM, prev_term)
            self.stop()
