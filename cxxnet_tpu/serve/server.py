"""Stdlib JSON front-end for the inference engine / replica fleet.

``http.server``-based so the engine is drivable end-to-end with zero new
dependencies (the same reason the IO pipeline is pure stdlib threading):

* ``POST /predict``  ``{"data": [[...], ...], "raw": 0|1,
  "timeout_ms": N?, "version": "rNNNN"?}`` -> ``{"pred": [...]}`` /
  ``{"prob": [[...]]}``
* ``POST /extract``  ``{"data": ..., "node": "name"}``
  -> ``{"features": [[...]]}``
* ``POST /generate`` ``{"prompt": [ids...], "max_new": N?,
  "deadline_ms": T?, "stream": 0|1, "version": "rNNNN"?}`` — LM
  serving (serve/lm/): streamed by default as ``Transfer-Encoding:
  chunked`` ndjson, ONE event per chunk flushed as each token lands
  (see serve/lm/stream.py for the event grammar), so clients measure
  TTFT / inter-token latency directly; ``stream: 0`` returns
  ``{"tokens": [...], "reason": "eos"|"length"}`` in one body. A
  client disconnect mid-stream cancels the sequence and frees its KV
  blocks. Requires an attached LM plane (``attach_lm`` /
  ``ReplicaPool.attach_lm``).
* ``GET  /healthz``  -> ``{"status": "ok"|"degraded"|"open"|"down", ...}``
* ``GET  /statz``    -> the ServingStats snapshot + breaker/queue state

The server fronts either ONE engine (``ServeServer(engine)``, the PR-1
layout byte-for-byte) or a replica fleet (``ServeServer(pool=...)``,
serve/fleet.py): with a pool, requests route by version pin -> breaker
state -> admission control -> least queue depth, ``/healthz`` aggregates
(the worst replica decides the top-level status, per-replica statuses
ride along) and ``/statz`` keeps the single-engine key layout at the top
level (summed) while gaining ``replicas`` / ``versions`` breakdowns.
A/B version pinning: the ``version`` JSON field or ``X-Model-Version``
header routes deterministically to replicas serving that checkpoint
round (unknown version -> 400).

Health semantics (what a load balancer keys routing on):

* ``ok``       (200) — dispatching normally;
* ``degraded`` (200) — still serving but impaired: the admitted-row
  queue is past ``degraded_queue_frac`` of its budget, the breaker is
  half-open (probing a recovering device), corrupt input records
  have been skipped this process (``recordio.skipped``), the
  latency-SLO burn rate is at/over ``slo_burn_degraded`` (the error
  budget is being eaten unsustainably fast), or — fleet mode — a
  replica is draining/reloading/degraded — keep routing, start
  paging;
* ``open``     (503) — the circuit breaker is open: dispatches are
  failing and requests are being rejected fast — route elsewhere;
* ``down``     (500) — the batcher worker is dead (fleet: the WORST
  replica is dead; the per-replica list shows which).

Error mapping: malformed request AND unknown pinned version 400,
backpressure / breaker-open / no-healthy-replica / admission-shed 503
(retry later), deadline exceeded 504, engine failure 500. Shutdown is
graceful: stop accepting, then drain the batcher(s) so queued requests
still get answers — and since SIGTERM/SIGINT handlers are installed at
``start()`` (main thread only), rolling restarts and container stops
take the same drain path as a programmatic ``stop()``.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from ..resilience import CircuitBreaker, CircuitOpen, counters
from ..telemetry import PROMETHEUS_CONTENT_TYPE, render_prometheus
from ..telemetry.disttrace import DISTTRACE
from ..telemetry.ledger import LEDGER, run_info
from ..telemetry.slo import SLOTracker
from ..telemetry.trace import TRACER
from .batcher import Backpressure, DeadlineExceeded, MicroBatcher
from .engine import InferenceEngine
from .fleet import NoHealthyReplica, ReplicaPool, UnknownVersion
from .stats import ServingStats


def _make_handler(server: "ServeServer"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):          # quiet per-request spam
            if not server.silent and server.verbose:
                BaseHTTPRequestHandler.log_message(self, fmt, *args)

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code >= 400:
                # error paths may leave the POST body unread; on an
                # HTTP/1.1 keep-alive socket those bytes would be parsed
                # as the next request line — drop the connection instead
                self.close_connection = True
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                code, payload = server.health()
                self._reply(code, payload)
            elif self.path == "/statz":
                self._reply(200, server.statz())
            elif self.path == "/metrics":
                # one scrape = the WHOLE process registry: serve,
                # resilience, checkpoint, io — not just this server's
                body = render_prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no such path {self.path}"})

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            if n <= 0 or n > server.max_body_bytes:
                raise ValueError(f"bad Content-Length {n}")
            return json.loads(self.rfile.read(n).decode("utf-8"))

        def do_POST(self):
            if self.path not in ("/predict", "/extract", "/generate"):
                self._reply(404, {"error": f"no such path {self.path}"})
                return
            # full request-lifecycle span (parse -> queue -> infer ->
            # respond nest inside it on this handler thread's track).
            # An incoming W3C ``traceparent`` header (tools/loadgen.py
            # sends one per request when tracing) parents this span
            # under the CLIENT's span, so the assembled fleet trace
            # links loadgen -> router -> queue -> infer -> respond
            # end-to-end; without the header this is a new root trace.
            # Falls back to the plain TRACER span when distributed
            # tracing is off.
            ctx = (DISTTRACE.extract(self.headers.get("traceparent"))
                   if DISTTRACE.enabled else None)
            with DISTTRACE.span("serve.request", cat="serve",
                                args={"path": self.path}, parent=ctx):
                self._handle_post()

        def _handle_post(self):
            try:
                req = self._read_json()
                if self.path != "/generate":  # generate carries token
                    data = np.asarray(req["data"], np.float32)  # ids,
                    if data.ndim == 1:        # not a float row matrix
                        data = data[None, :]
                timeout_ms = req.get("timeout_ms")
                # A/B pin: JSON field wins over the header (explicit in
                # the payload beats ambient routing config)
                version = req.get("version") \
                    or self.headers.get("X-Model-Version") or None
                # hard cap so a wedged worker can't hang handler threads
                # forever (batcher deadlines are the soft mechanism)
                if self.path == "/generate":
                    self._handle_generate(req, version)
                elif self.path == "/extract":
                    node = req.get("node", "top")
                    fut = server.submit(data, "extract", node,
                                        timeout_ms=timeout_ms,
                                        version=version)
                    out = fut.result(timeout=server.result_timeout_s)
                    with TRACER.span("serve.respond", cat="serve"):
                        self._reply(200, {"node": node,
                                          "features": out.tolist()})
                else:
                    kind = "raw" if int(req.get("raw", 0)) else "predict"
                    fut = server.submit(data, kind,
                                        timeout_ms=timeout_ms,
                                        version=version)
                    out = fut.result(timeout=server.result_timeout_s)
                    key = "prob" if kind == "raw" else "pred"
                    with TRACER.span("serve.respond", cat="serve"):
                        self._reply(200, {key: out.tolist()})
            except UnknownVersion as e:
                self._reply(400, {"error": str(e)})
            except (Backpressure, CircuitOpen, NoHealthyReplica) as e:
                self._reply(503, {"error": str(e)})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _handle_generate(self, req: dict, version) -> None:
            from .lm.stream import LAST_CHUNK, chunk, encode_event
            prompt = req.get("prompt")
            if not isinstance(prompt, (list, tuple)) or not prompt:
                raise ValueError(
                    "generate needs a non-empty integer list 'prompt'")
            handle = server.submit_lm(
                [int(t) for t in prompt], max_new=req.get("max_new"),
                deadline_ms=req.get("deadline_ms"), version=version)
            if not int(req.get("stream", 1)):
                try:
                    done = handle.result(timeout=server.result_timeout_s)
                except TimeoutError:
                    # slow generation outlived the handler budget: evict
                    # it (freeing its decode row + KV blocks) instead of
                    # letting it run on after the client got an error,
                    # and surface the standard 504 like any deadline
                    handle.cancel()
                    raise DeadlineExceeded(
                        "generation exceeded result_timeout_s="
                        f"{server.result_timeout_s}")
                with TRACER.span("serve.respond", cat="serve"):
                    self._reply(200, {"tokens": done["tokens"],
                                      "reason": done["reason"]})
                return
            # headers are committed from here on: failures become
            # in-band error events (already pushed by the scheduler) or
            # a dropped connection — never a second status line
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                with TRACER.span("serve.stream", cat="serve"):
                    for ev in handle.events(
                            timeout=server.result_timeout_s):
                        self.wfile.write(chunk(encode_event(ev)))
                        self.wfile.flush()     # per-token: TTFT is real
                    self.wfile.write(LAST_CHUNK)
                    self.wfile.flush()
            except (TimeoutError, OSError):
                # client gone (or stream wedged): release the decode
                # slot + KV blocks instead of generating into the void
                handle.cancel()
                self.close_connection = True

    return Handler


class ServeServer:
    """Engine (or replica pool) + HTTP front-end, with a periodic stats
    log line (the serving analog of the trainer's round metric line).

    Exactly one of ``engine`` / ``pool`` must be given. The single-
    engine form keeps the PR-1 surface byte-for-byte; the pool form
    routes through :class:`fleet.ReplicaPool` (each replica owns its
    batcher/breaker/SLO — the pool-level knobs here are ignored because
    they were applied per replica at pool build time). An optional
    ``reload_watcher`` (serve/reload.py) is lifecycle-managed: started
    with the server, stopped (before the drain) on shutdown, and
    surfaced in ``/statz`` under ``"reload"``.
    """

    def __init__(self, engine: Optional[InferenceEngine] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 max_batch: Optional[int] = None,
                 max_latency_ms: float = 5.0,
                 max_queue_rows: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 log_interval_s: float = 30.0,
                 silent: bool = False, verbose: bool = False,
                 max_body_bytes: int = 64 << 20,
                 result_timeout_s: float = 120.0,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 10.0,
                 degraded_queue_frac: float = 0.8,
                 slo_ms: float = 0.0,
                 slo_target: float = 0.99,
                 slo_window_s: float = 60.0,
                 slo_burn_degraded: float = 2.0,
                 pool: Optional[ReplicaPool] = None,
                 reload_watcher=None,
                 handle_signals: bool = True):
        if (engine is None) == (pool is None):
            raise ValueError("ServeServer takes exactly one of "
                             "engine= or pool=")
        self.engine = engine
        self.pool = pool
        self.reload_watcher = reload_watcher
        self.silent = silent
        self.verbose = verbose
        self.max_body_bytes = max_body_bytes
        self.result_timeout_s = result_timeout_s
        self.log_interval_s = log_interval_s
        self.degraded_queue_frac = float(degraded_queue_frac)
        # latency SLO: every terminal outcome (ok/over-latency/reject/
        # failure) is classified good/bad; the rolling burn rate feeds
        # /healthz BELOW — degradation fires while the breaker is still
        # closed, which is what makes it an admission-control signal
        # rather than a post-mortem
        self.slo_burn_degraded = float(slo_burn_degraded)
        self.slo: Optional[SLOTracker] = None
        self.breaker: Optional[CircuitBreaker] = None
        self.batcher: Optional[MicroBatcher] = None
        self.stats: Optional[ServingStats] = None
        # single-engine LM plane (serve/lm LMScheduler) — attach_lm();
        # fleet mode keeps it per replica instead
        self.lm = None
        if engine is not None:
            self.stats = engine.stats
            if slo_ms > 0:
                self.slo = SLOTracker(slo_ms, target=slo_target,
                                      window_s=slo_window_s,
                                      instance=self.stats.instance)
                self.stats.slo = self.slo
            # breaker_threshold = 0 disables circuit breaking entirely
            self.breaker = (
                CircuitBreaker(failure_threshold=breaker_threshold,
                               reset_timeout_s=breaker_reset_s)
                if breaker_threshold > 0 else None)
            self.batcher = MicroBatcher(
                engine, max_batch=max_batch,
                max_latency_ms=max_latency_ms,
                max_queue_rows=max_queue_rows,
                default_timeout_ms=default_timeout_ms, stats=self.stats,
                breaker=self.breaker)
        # degradation is reported relative to THIS server's lifetime —
        # corrupt records skipped before serving started (e.g. during
        # training in the same process) are not this endpoint's problem
        self._skipped_base = counters.get("recordio.skipped")
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None
        self._log_stop = threading.Event()
        self._log_thread: Optional[threading.Thread] = None
        # graceful-shutdown plumbing: signal handlers set _stop_evt; a
        # watcher thread (and/or serve_until_interrupt) runs the actual
        # stop(), which is idempotent
        self.handle_signals = bool(handle_signals)
        self._stop_evt = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = False
        self._stop_done = threading.Event()
        self._prev_handlers: Dict[int, object] = {}

    # -- request routing -------------------------------------------------
    def submit(self, data, kind: str = "predict",
               node: Optional[str] = None,
               timeout_ms: Optional[float] = None,
               version: Optional[str] = None):
        """One entry point for both topologies: the pool routes, the
        single engine goes straight to its batcher (where a version pin
        only matches the engine's own weights)."""
        if self.pool is not None:
            return self.pool.submit(data, kind, node,
                                    timeout_ms=timeout_ms,
                                    version=version)
        if version is not None \
                and version != self.engine.weights_version:
            raise UnknownVersion(
                f"no replica serves model version {version!r}; "
                f"available: [{self.engine.weights_version!r}]")
        return self.batcher.submit(data, kind, node,
                                   timeout_ms=timeout_ms)

    # -- LM serving plane -------------------------------------------------
    def attach_lm(self, lm_cfg) -> "ServeServer":
        """Bring up the LM plane (parse_lm_serve_config output): per
        replica in fleet mode, one scheduler on the single engine
        otherwise. Same idle-probe + stats wiring either way."""
        if self.pool is not None:
            self.pool.attach_lm(lm_cfg)
            return self
        from .lm import LMEngine, LMScheduler
        if self.lm is not None:
            raise RuntimeError("LM plane already attached")
        lme = LMEngine(self.engine, lm_cfg)
        sched = LMScheduler(lme, lm_cfg)
        sched.start()
        self.batcher.add_idle_probe(sched.live_count)
        self.stats.lm = sched.snapshot
        self.lm = sched
        return self

    def submit_lm(self, prompt, max_new: Optional[int] = None,
                  deadline_ms: Optional[float] = None,
                  version: Optional[str] = None):
        """Route one generation request; returns its StreamHandle."""
        if self.pool is not None:
            return self.pool.submit_lm(prompt, max_new=max_new,
                                       deadline_ms=deadline_ms,
                                       version=version)
        if self.lm is None:
            raise NoHealthyReplica(
                "no LM plane attached (server.attach_lm / "
                "ReplicaPool.attach_lm)")
        if version is not None \
                and version != self.engine.weights_version:
            raise UnknownVersion(
                f"no replica serves model version {version!r}; "
                f"available: [{self.engine.weights_version!r}]")
        return self.lm.submit(prompt, max_new=max_new,
                              deadline_ms=deadline_ms)

    # -- health ----------------------------------------------------------
    def health(self) -> Tuple[int, Dict]:
        """``ok | degraded | open | down`` + the signals behind the call
        (see module docstring for the load-balancer semantics)."""
        skipped = counters.get("recordio.skipped") - self._skipped_base
        if self.pool is not None:
            agg = self.pool.health()
            status = agg["status"]
            if status == "ok" and skipped > 0:
                status = "degraded"
            code = {"ok": 200, "degraded": 200,
                    "open": 503, "down": 500}[status]
            out = {
                "status": status,
                "ok": status == "ok",       # back-compat boolean
                "replicas": agg["replicas"],
                "versions": agg["versions"],
                "skipped_records": skipped,
            }
            return code, out
        alive = self.batcher is not None \
            and self.batcher._thread.is_alive()
        queued = self.batcher.queued_rows if alive else 0
        queue_frac = queued / max(1, self.batcher.max_queue_rows)
        # effective_state: an open breaker past its reset timeout reads
        # half_open (-> degraded, 200), so a load balancer that drained
        # this node on 503 resumes the trickle of traffic the recovery
        # probe needs — raw "open" would hold it out of rotation forever
        breaker_state = (self.breaker.effective_state()
                         if self.breaker is not None else "disabled")
        burn = self.slo.burn_rate() if self.slo is not None else 0.0
        if not alive:
            status, code = "down", 500
        elif breaker_state == "open":
            status, code = "open", 503
        elif (breaker_state == "half_open"
              or queue_frac >= self.degraded_queue_frac
              or skipped > 0
              or burn >= self.slo_burn_degraded):
            status, code = "degraded", 200
        else:
            status, code = "ok", 200
        out = {
            "status": status,
            "ok": status == "ok",           # back-compat boolean
            "breaker": breaker_state,
            "queued_rows": queued,
            "queue_frac": round(queue_frac, 4),
            "skipped_records": skipped,
        }
        if self.slo is not None:
            out["slo_burn_rate"] = round(burn, 4)
        return code, out

    def statz(self) -> Dict:
        """Stats snapshot + the resilience state alongside it. Fleet
        mode keeps the single-engine key layout at the top (aggregated)
        and adds ``replicas`` / ``versions`` / ``reload``."""
        if self.pool is not None:
            out = self.pool.snapshot()
            out["queue"] = {
                "rows": sum(r.batcher.queued_rows
                            for r in self.pool.replicas),
                "max_rows": sum(r.batcher.max_queue_rows
                                for r in self.pool.replicas)}
        else:
            out = self.stats.snapshot()
            if self.breaker is not None:
                out["breaker"] = self.breaker.snapshot()
            if self.slo is not None:
                out["slo"] = self.slo.snapshot()
            out["queue"] = {"rows": self.batcher.queued_rows,
                            "max_rows": self.batcher.max_queue_rows}
        if self.reload_watcher is not None:
            out["reload"] = self.reload_watcher.snapshot()
        out["counters"] = counters.snapshot()
        # run identity: joins this process's scraped/statz numbers with
        # the run ledger and the training task's series (same run_id)
        out["run"] = run_info()
        return out

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServeServer":
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()
        if self.log_interval_s > 0 and not self.silent:
            self._log_thread = threading.Thread(
                target=self._log_loop, daemon=True, name="serve-statlog")
            self._log_thread.start()
        if self.reload_watcher is not None:
            self.reload_watcher.start()
        if self.handle_signals:
            self._install_signal_handlers()
        n_rep = len(self.pool.replicas) if self.pool is not None else 1
        LEDGER.event(
            "serve_start", port=self.port, replicas=n_rep,
            versions=(self.pool.versions() if self.pool is not None
                      else None),
            reload_s=(self.reload_watcher.interval_s
                      if self.reload_watcher is not None else 0))
        if not self.silent:
            print(f"serving on http://{self.httpd.server_address[0]}:"
                  f"{self.port} (/predict /extract /healthz /statz), "
                  f"{n_rep} replica(s)",
                  flush=True)
        return self

    def _log_loop(self) -> None:
        while not self._log_stop.wait(self.log_interval_s):
            print(self.log_line(), flush=True)

    def log_line(self) -> str:
        if self.pool is None:
            return self.stats.log_line()
        s = self.pool.snapshot()
        line = ("serve-fleet[%dx]\tqps:%.2f\tp50_ms:%.2f\tp99_ms:%.2f"
                "\tfill:%.3f\tok:%d\tfailed:%d\tversions:%s" % (
                    len(self.pool.replicas), s["qps"],
                    s["latency_ms"]["p50"], s["latency_ms"]["p99"],
                    s["batches"]["fill_ratio"], s["requests"]["ok"],
                    s["requests"]["failed"],
                    ",".join(sorted(s["versions"]) or ["init"])))
        if "cascade" in s:
            # two-tier cascade router (serve/cascade.py): the
            # escalation rate is the cost-per-request lever, so the
            # periodic line carries it next to the latency numbers
            line += "\tesc_rate:%.3f" % s["cascade"]["escalation_rate"]
        return line

    # -- signals ---------------------------------------------------------
    def _install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> the same graceful drain as a programmatic
        ``stop()``: rolling restarts and container stops must not drop
        the requests already admitted. Main thread only (CPython's
        signal contract); embedded servers on other threads simply skip
        — their host process owns signal policy. The handler restores
        the previous handlers FIRST (it runs on the main thread, the
        only place that's legal — a stop() driven from the sigwatch
        thread could never do it), sets the event, then CHAINS to the
        previous handler: when train+serve share a process the elastic
        preemption handler (elastic/preempt.py) was installed before
        this one, and one SIGTERM must both drain the server and start
        the grace-checkpoint path — neither concern may clobber the
        other (regression: tests/test_serve_fleet.py,
        tests/test_elastic.py). A second signal still gets the host's
        original behavior (e.g. force-kill), and a drained server
        never keeps swallowing the process's signals."""
        import signal
        if threading.current_thread() is not threading.main_thread():
            return
        # bound HERE, at install time, never inside the handler: a
        # first-ever import executed in signal context could observe a
        # partially initialized module and blow up mid-drain
        from ..elastic.preempt import chain_signal_handler

        def _sig(signum, _frame):
            prev = self._prev_handlers.get(signum)
            self._restore_signal_handlers()
            self._stop_evt.set()
            chain_signal_handler(signum, prev)

        for signum in (signal.SIGINT, signal.SIGTERM):
            try:
                self._prev_handlers[signum] = signal.signal(signum, _sig)
            except (ValueError, OSError):     # non-main thread race
                return
        threading.Thread(target=self._sig_watch, daemon=True,
                         name="serve-sigwatch").start()

    def _sig_watch(self) -> None:
        self._stop_evt.wait()
        self.stop()

    def _restore_signal_handlers(self) -> None:
        import signal
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers = {}

    def stop(self) -> None:
        """Graceful + idempotent: stop accepting, stop the reload
        watcher (a weight swap must not race the teardown), drain the
        batcher(s), then report. Safe to call from the signal watcher
        AND serve_until_interrupt at once: the loser of the race BLOCKS
        until the winner's drain completes — a caller returning early
        could let the process exit while the daemon sigwatch thread is
        still mid-drain, dropping admitted requests."""
        with self._stop_lock:
            if self._stopped:
                # no timeout: a large fleet's serial drain can legally
                # take minutes, and returning early would let the
                # process exit mid-drain; the winner's finally ALWAYS
                # sets the event, even when its teardown raises
                self._stop_done.wait()
                return
            self._stopped = True
        self._stop_evt.set()
        self._log_stop.set()
        try:
            if self.reload_watcher is not None:
                self.reload_watcher.stop()
            self.httpd.shutdown()
            self.httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=10)
            if self.pool is not None:
                self.pool.close(drain=True)
            else:
                # LM plane first: its live sequences hold KV blocks the
                # batcher's idle probe watches (same order as
                # Replica.close)
                if self.lm is not None:
                    self.lm.stop(drain=True)
                    self.lm.engine.close()
                self.batcher.close(drain=True)
            if not self.silent:
                print(self.log_line(), flush=True)
            if self.stats is not None:
                # drop this engine's per-instance series from the
                # registry — a stopped server's frozen gauges must not
                # be scraped forever (pool replicas unregister in
                # pool.close)
                self.stats.unregister()
            if threading.current_thread() is threading.main_thread():
                self._restore_signal_handlers()
        finally:
            self._stop_done.set()

    def serve_until_interrupt(self) -> None:
        """Foreground loop for ``task = serve``: block until SIGINT/
        SIGTERM (handlers installed at start(); installed here as a
        fallback when start() ran with handle_signals=False), then shut
        down gracefully."""
        if not self._prev_handlers and not self._stopped:
            self._install_signal_handlers()
        try:
            self._stop_evt.wait()
        finally:
            self.stop()
