"""Serving observability: QPS, latency percentiles, batch fill, cache hits.

Since PR 4 this is a VIEW over the process-wide telemetry registry
(:mod:`cxxnet_tpu.telemetry.registry`), not parallel bookkeeping: every
counter here is a ``cxxnet_serve_*`` registry metric (labeled by engine
instance, so several engines in one process stay distinguishable in a
``/metrics`` scrape), and :meth:`snapshot` — the ``/statz`` payload —
reads those same series back with its ORIGINAL key layout, so PR-1
clients and smoke tools see byte-identical structure. Request latencies
additionally feed a registry histogram
(``cxxnet_serve_request_latency_seconds``); the exact p50/p95/p99 the
snapshot reports still come from a bounded local reservoir (percentiles
from log buckets would be quantized).

All methods are thread-safe — the batcher worker, HTTP handler threads,
and the engine all write here.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..telemetry.registry import REGISTRY, MetricRegistry

_INSTANCE_SEQ = itertools.count()


class ServingStats:
    """Rolling serving metrics.

    * latency: bounded sample reservoir (last ``max_samples`` request
      latencies) -> p50/p95/p99 at snapshot time, plus the registry
      latency histogram;
    * QPS: completion timestamps within a rolling ``qps_window_s`` window;
    * batch fill: real rows / padded bucket rows, per dispatch;
    * coalescing: requests folded into each device call;
    * compile cache: hit/miss/evict counters fed by the engine.
    """

    def __init__(self, max_samples: int = 4096, qps_window_s: float = 60.0,
                 registry: Optional[MetricRegistry] = None):
        self._lock = threading.Lock()
        self._t0 = time.time()
        self.qps_window_s = qps_window_s
        self._lat: deque = deque(maxlen=max_samples)       # seconds
        self._done_ts: deque = deque(maxlen=65536)         # completion times
        reg = registry or REGISTRY
        self.instance = str(next(_INSTANCE_SEQ))
        eng = (self.instance,)
        # every (family, label-values) this instance creates, so
        # unregister() can drop the series when the engine goes away —
        # otherwise each dead instance's ~20 series (stale gauges
        # included) would be scraped forever
        self._series = []

        def _track(fam, *vals):
            self._series.append((fam, vals))
            return fam.labels(*vals)
        req = reg.counter("cxxnet_serve_requests_total",
                          "Serve requests by outcome",
                          labels=("engine", "result"))
        self._c_total = _track(req, self.instance, "received")
        self._c_ok = _track(req, self.instance, "ok")
        self._c_rej_bp = _track(req, self.instance, "rejected_backpressure")
        self._c_rej_dl = _track(req, self.instance, "rejected_deadline")
        self._c_rej_br = _track(req, self.instance, "rejected_breaker")
        self._c_failed = _track(req, self.instance, "failed")
        self._c_batches = _track(reg.counter(
            "cxxnet_serve_batches_dispatched_total",
            "Device dispatches", labels=("engine",)), *eng)
        self._c_req_batched = _track(reg.counter(
            "cxxnet_serve_requests_batched_total",
            "Requests folded into dispatches",
            labels=("engine",)), *eng)
        rows = reg.counter("cxxnet_serve_batch_rows_total",
                           "Dispatched rows (real vs padded-bucket)",
                           labels=("engine", "kind"))
        self._c_rows_real = _track(rows, self.instance, "real")
        self._c_rows_padded = _track(rows, self.instance, "padded")
        self._c_coalesced = _track(reg.counter(
            "cxxnet_serve_batches_coalesced_total",
            "Dispatches that folded >= 2 requests",
            labels=("engine",)), *eng)
        cache = reg.counter("cxxnet_serve_cache_events_total",
                            "Compile-cache events",
                            labels=("engine", "event"))
        self._c_hit = _track(cache, self.instance, "hit")
        self._c_miss = _track(cache, self.instance, "miss")
        self._c_evict = _track(cache, self.instance, "evict")
        self._g_csize = _track(reg.gauge("cxxnet_serve_cache_size",
                                         "Compiled executables cached",
                                         labels=("engine",)), *eng)
        self._g_ccap = _track(reg.gauge("cxxnet_serve_cache_capacity",
                                        "Compile-cache capacity",
                                        labels=("engine",)), *eng)
        self._h_lat = _track(reg.histogram(
            "cxxnet_serve_request_latency_seconds",
            "End-to-end request latency (submit -> result)",
            labels=("engine",)), *eng)
        # optional latency-SLO tracker (telemetry.slo.SLOTracker),
        # attached by ServeServer when serve_slo_ms is configured;
        # every terminal outcome recorded here feeds it
        self.slo = None
        # optional LM-serving probe (serve/lm LMScheduler.snapshot),
        # attached by ReplicaPool.attach_lm; snapshot() inlines it so
        # /statz shows decode rows / KV occupancy next to batch fill
        self.lm = None

    # -- registry-backed attribute views ---------------------------------
    @property
    def requests_total(self) -> int:
        return int(self._c_total.value)

    @property
    def requests_ok(self) -> int:
        return int(self._c_ok.value)

    @property
    def rejected_backpressure(self) -> int:
        return int(self._c_rej_bp.value)

    @property
    def rejected_deadline(self) -> int:
        return int(self._c_rej_dl.value)

    @property
    def rejected_breaker(self) -> int:
        return int(self._c_rej_br.value)

    @property
    def failed(self) -> int:
        return int(self._c_failed.value)

    @property
    def batches_dispatched(self) -> int:
        return int(self._c_batches.value)

    @property
    def rows_real(self) -> int:
        return int(self._c_rows_real.value)

    @property
    def rows_padded(self) -> int:
        return int(self._c_rows_padded.value)

    @property
    def requests_batched(self) -> int:
        return int(self._c_req_batched.value)

    @property
    def batches_coalesced_ge2(self) -> int:
        return int(self._c_coalesced.value)

    @property
    def cache_hits(self) -> int:
        return int(self._c_hit.value)

    @property
    def cache_misses(self) -> int:
        return int(self._c_miss.value)

    @property
    def cache_evictions(self) -> int:
        return int(self._c_evict.value)

    @property
    def cache_size(self) -> int:
        return int(self._g_csize.value)

    @property
    def cache_capacity(self) -> int:
        return int(self._g_ccap.value)

    def unregister(self) -> None:
        """Drop this instance's series from the registry (ServeServer.
        stop() calls this): a torn-down engine's numbers — stale cache
        gauges especially — must not appear in scrapes forever. Held
        child references keep working; they just stop exporting."""
        for fam, vals in self._series:
            fam.remove_labels(*vals)
        if self.slo is not None:
            self.slo.unregister()

    # -- recording -------------------------------------------------------
    def record_request(self) -> None:
        self._c_total.inc()

    def record_reject(self, kind: str) -> None:
        if kind == "backpressure":
            self._c_rej_bp.inc()
        elif kind == "breaker":
            self._c_rej_br.inc()
        else:
            self._c_rej_dl.inc()
        if self.slo is not None:       # a rejected client missed the SLO
            self.slo.record(ok=False)

    def record_failure(self) -> None:
        self._c_failed.inc()
        if self.slo is not None:
            self.slo.record(ok=False)

    def record_done(self, latency_s: float) -> None:
        now = time.time()
        self._c_ok.inc()
        self._h_lat.observe(latency_s)
        if self.slo is not None:
            self.slo.record(latency_s=latency_s, ok=True)
        with self._lock:
            self._lat.append(latency_s)
            self._done_ts.append(now)

    def record_batch(self, n_requests: int, rows_real: int,
                     rows_bucket: int) -> None:
        self._c_batches.inc()
        self._c_req_batched.inc(n_requests)
        self._c_rows_real.inc(rows_real)
        self._c_rows_padded.inc(rows_bucket)
        if n_requests >= 2:
            self._c_coalesced.inc()

    def record_cache(self, hit: Optional[bool] = None,
                     size: Optional[int] = None,
                     capacity: Optional[int] = None,
                     evicted: bool = False) -> None:
        """``hit=None`` updates geometry only (no hit/miss tick)."""
        if hit is True:
            self._c_hit.inc()
        elif hit is False:
            self._c_miss.inc()
        if evicted:
            self._c_evict.inc()
        if size is not None:
            self._g_csize.set(size)
        if capacity is not None:
            self._g_ccap.set(capacity)

    # -- reading ---------------------------------------------------------
    def latency_samples(self) -> List[float]:
        """Copy of the bounded latency reservoir (seconds) — the fleet
        pool concatenates these across replicas so aggregate percentiles
        come from pooled observations, not averaged percentiles."""
        with self._lock:
            return list(self._lat)

    def snapshot_uptime(self) -> float:
        return max(time.time() - self._t0, 1e-9)

    def recent_qps(self) -> float:
        """Completions per second over the rolling window (the same
        number snapshot()['qps'] reports)."""
        now = time.time()
        uptime = max(now - self._t0, 1e-9)
        window = min(self.qps_window_s, uptime)
        if not window:
            return 0.0
        cutoff = now - window
        with self._lock:
            recent = sum(1 for t in self._done_ts if t >= cutoff)
        return recent / window

    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(q * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def snapshot(self) -> Dict:
        """One coherent dict of everything — the ``/statz`` payload,
        with the exact PR-1 key layout. Counter reads are individually
        locked registry lookups; the deque copy happens under this
        object's lock and the percentile sort outside it, so a
        monitoring poller never stalls the dispatch hot path."""
        with self._lock:
            lat_raw = list(self._lat)
        uptime = self.snapshot_uptime()
        qps = self.recent_qps()       # one definition of the window
        lat = sorted(lat_raw)
        rows_real, rows_padded = self.rows_real, self.rows_padded
        b_disp, req_batched = self.batches_dispatched, self.requests_batched
        fill = rows_real / rows_padded if rows_padded else 0.0
        rpb = req_batched / b_disp if b_disp else 0.0
        return {
            "uptime_s": round(uptime, 3),
            "requests": {
                "total": self.requests_total,
                "ok": self.requests_ok,
                "rejected_backpressure": self.rejected_backpressure,
                "rejected_deadline": self.rejected_deadline,
                "rejected_breaker": self.rejected_breaker,
                "failed": self.failed,
            },
            "qps": round(qps, 3),
            "latency_ms": {
                "p50": round(1e3 * self._pct(lat, 0.50), 3),
                "p95": round(1e3 * self._pct(lat, 0.95), 3),
                "p99": round(1e3 * self._pct(lat, 0.99), 3),
                "mean": round(1e3 * sum(lat) / len(lat), 3)
                        if lat else 0.0,
                "samples": len(lat),
            },
            "batches": {
                "dispatched": b_disp,
                "coalesced_ge2": self.batches_coalesced_ge2,
                "avg_requests_per_batch": round(rpb, 3),
                "fill_ratio": round(fill, 4),
                "rows_real": rows_real,
                "rows_padded": rows_padded,
            },
            "compile_cache": {
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "evictions": self.cache_evictions,
                "size": self.cache_size,
                "capacity": self.cache_capacity,
            },
            **({"lm": self.lm()} if self.lm is not None else {}),
        }

    def log_line(self) -> str:
        """One-line periodic log, same spirit as the trainer's round line:
        ``serve[   12 sec]\tqps:3.2\tp50_ms:1.4 ...``"""
        s = self.snapshot()
        return ("serve[%5d sec]\tqps:%.2f\tp50_ms:%.2f\tp95_ms:%.2f"
                "\tp99_ms:%.2f\tfill:%.3f\tcache_hit:%d\tcache_miss:%d"
                "\tok:%d\trej:%d" % (
                    s["uptime_s"], s["qps"], s["latency_ms"]["p50"],
                    s["latency_ms"]["p95"], s["latency_ms"]["p99"],
                    s["batches"]["fill_ratio"], s["compile_cache"]["hits"],
                    s["compile_cache"]["misses"], s["requests"]["ok"],
                    s["requests"]["rejected_backpressure"]
                    + s["requests"]["rejected_deadline"]
                    + s["requests"]["rejected_breaker"]))
