"""Serving observability: QPS, latency percentiles, batch fill, cache hits.

The counters are the serving analog of the trainer's per-round metric line
(trainer.py round metrics): everything lands in one dict snapshot
(``/statz``) and one periodic one-line log. All methods are thread-safe —
the batcher worker, HTTP handler threads, and the engine all write here.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional


class ServingStats:
    """Rolling serving metrics.

    * latency: bounded sample reservoir (last ``max_samples`` request
      latencies) -> p50/p95/p99 at snapshot time;
    * QPS: completion timestamps within a rolling ``qps_window_s`` window;
    * batch fill: real rows / padded bucket rows, per dispatch;
    * coalescing: requests folded into each device call;
    * compile cache: hit/miss/evict counters fed by the engine.
    """

    def __init__(self, max_samples: int = 4096, qps_window_s: float = 60.0):
        self._lock = threading.Lock()
        self._t0 = time.time()
        self.qps_window_s = qps_window_s
        self._lat: deque = deque(maxlen=max_samples)       # seconds
        self._done_ts: deque = deque(maxlen=65536)         # completion times
        # request counters
        self.requests_total = 0
        self.requests_ok = 0
        self.rejected_backpressure = 0
        self.rejected_deadline = 0
        self.rejected_breaker = 0
        self.failed = 0
        # batch counters
        self.batches_dispatched = 0
        self.rows_real = 0
        self.rows_padded = 0          # bucket rows incl. padding
        self.requests_batched = 0     # requests folded into dispatches
        self.batches_coalesced_ge2 = 0
        # compile cache counters
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.cache_size = 0
        self.cache_capacity = 0

    # -- recording -------------------------------------------------------
    def record_request(self) -> None:
        with self._lock:
            self.requests_total += 1

    def record_reject(self, kind: str) -> None:
        with self._lock:
            if kind == "backpressure":
                self.rejected_backpressure += 1
            elif kind == "breaker":
                self.rejected_breaker += 1
            else:
                self.rejected_deadline += 1

    def record_failure(self) -> None:
        with self._lock:
            self.failed += 1

    def record_done(self, latency_s: float) -> None:
        now = time.time()
        with self._lock:
            self.requests_ok += 1
            self._lat.append(latency_s)
            self._done_ts.append(now)

    def record_batch(self, n_requests: int, rows_real: int,
                     rows_bucket: int) -> None:
        with self._lock:
            self.batches_dispatched += 1
            self.requests_batched += n_requests
            self.rows_real += rows_real
            self.rows_padded += rows_bucket
            if n_requests >= 2:
                self.batches_coalesced_ge2 += 1

    def record_cache(self, hit: Optional[bool] = None,
                     size: Optional[int] = None,
                     capacity: Optional[int] = None,
                     evicted: bool = False) -> None:
        """``hit=None`` updates geometry only (no hit/miss tick)."""
        with self._lock:
            if hit is True:
                self.cache_hits += 1
            elif hit is False:
                self.cache_misses += 1
            if evicted:
                self.cache_evictions += 1
            if size is not None:
                self.cache_size = size
            if capacity is not None:
                self.cache_capacity = capacity

    # -- reading ---------------------------------------------------------
    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(q * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def snapshot(self) -> Dict:
        """One coherent dict of everything — the ``/statz`` payload.
        Only cheap copies happen under the lock; the deque scan and the
        percentile sort run outside it so a monitoring poller never
        stalls the dispatch hot path's record_* calls."""
        with self._lock:
            now = time.time()
            lat_raw = list(self._lat)
            done_ts = list(self._done_ts)
            counters = (self.requests_total, self.requests_ok,
                        self.rejected_backpressure, self.rejected_deadline,
                        self.rejected_breaker,
                        self.failed, self.batches_dispatched,
                        self.requests_batched, self.rows_real,
                        self.rows_padded, self.batches_coalesced_ge2,
                        self.cache_hits, self.cache_misses,
                        self.cache_evictions, self.cache_size,
                        self.cache_capacity)
        (req_total, req_ok, rej_bp, rej_dl, rej_br, failed, b_disp,
         req_batched, rows_real, rows_padded, coalesced, c_hit, c_miss,
         c_evict, c_size, c_cap) = counters
        uptime = max(now - self._t0, 1e-9)
        window = min(self.qps_window_s, uptime)
        cutoff = now - window
        recent = sum(1 for t in done_ts if t >= cutoff)
        lat = sorted(lat_raw)
        fill = rows_real / rows_padded if rows_padded else 0.0
        rpb = req_batched / b_disp if b_disp else 0.0
        return {
            "uptime_s": round(uptime, 3),
            "requests": {
                "total": req_total,
                "ok": req_ok,
                "rejected_backpressure": rej_bp,
                "rejected_deadline": rej_dl,
                "rejected_breaker": rej_br,
                "failed": failed,
            },
            "qps": round(recent / window, 3) if window else 0.0,
            "latency_ms": {
                "p50": round(1e3 * self._pct(lat, 0.50), 3),
                "p95": round(1e3 * self._pct(lat, 0.95), 3),
                "p99": round(1e3 * self._pct(lat, 0.99), 3),
                "mean": round(1e3 * sum(lat) / len(lat), 3)
                        if lat else 0.0,
                "samples": len(lat),
            },
            "batches": {
                "dispatched": b_disp,
                "coalesced_ge2": coalesced,
                "avg_requests_per_batch": round(rpb, 3),
                "fill_ratio": round(fill, 4),
                "rows_real": rows_real,
                "rows_padded": rows_padded,
            },
            "compile_cache": {
                "hits": c_hit,
                "misses": c_miss,
                "evictions": c_evict,
                "size": c_size,
                "capacity": c_cap,
            },
        }

    def log_line(self) -> str:
        """One-line periodic log, same spirit as the trainer's round line:
        ``serve[   12 sec]\tqps:3.2\tp50_ms:1.4 ...``"""
        s = self.snapshot()
        return ("serve[%5d sec]\tqps:%.2f\tp50_ms:%.2f\tp95_ms:%.2f"
                "\tp99_ms:%.2f\tfill:%.3f\tcache_hit:%d\tcache_miss:%d"
                "\tok:%d\trej:%d" % (
                    s["uptime_s"], s["qps"], s["latency_ms"]["p50"],
                    s["latency_ms"]["p95"], s["latency_ms"]["p99"],
                    s["batches"]["fill_ratio"], s["compile_cache"]["hits"],
                    s["compile_cache"]["misses"], s["requests"]["ok"],
                    s["requests"]["rejected_backpressure"]
                    + s["requests"]["rejected_deadline"]
                    + s["requests"]["rejected_breaker"]))
