"""Zero-downtime hot weight reload for the serving fleet.

Train and serve the same model concurrently: a trainer writes
``%04d.model`` checkpoints into ``model_dir`` while this watcher polls
the directory and rolls every new round into the live replica pool —
one replica at a time, each drained before its weights swap, so traffic
never sees a dropped request or a half-loaded model.

Safety comes from the PR-3 checkpoint machinery, not from trust in the
writer: the scan is :func:`checkpoint.find_latest_valid` (sha256-digest
verification, torn/corrupt archives skipped with fallback a round), so a
mid-write or truncated checkpoint can never be served. The cheap
:func:`checkpoint.find_latest` scan runs first — the expensive
read+verify only happens when the directory actually has a newer round
than the pool serves.

Shard-set rounds ride the same two scans: ``find_latest`` counts a
``r%04d/`` directory only once its manifest is published (an
in-progress set never even triggers the verify), and
``find_latest_valid`` quorum-validates the whole set before any replica
is touched (``load_for_inference`` additionally skips all-optimizer
shard files when an engine restores directly from a path).
``blob_digest`` over a shard-set meta equals the same state's blob
digest, so version/digest labels stay format-independent.

A/B pinning rides the same path: with ``ab_replicas = k``, a reload
updates only the k-replica canary subset, leaving the rest on the
previous version — two model versions serve side by side (per-version
stats in /statz, deterministic routing via the request's ``version``
field / ``X-Model-Version`` header) until :meth:`ReloadWatcher.promote`
(or a non-A/B reload) rolls the rest forward.

Every reload lands a ``weights_reload`` ledger event (old/new round +
content digest, per replica) between the ``replica_state`` transitions,
so ``tools/report.py`` renders the serving timeline next to the
training incident timeline.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..telemetry.disttrace import DISTTRACE
from ..telemetry.ledger import LEDGER
from .. import checkpoint as ckpt
from .fleet import ReplicaPool, version_name


class ReloadWatcher:
    """Poll ``model_dir`` and roll new checkpoints into ``pool``.

    ``interval_s <= 0`` disables the background thread — the watcher is
    then a manual handle (``check_once()``), which is what tests and the
    smoke tool drive for determinism.
    """

    def __init__(self, pool: ReplicaPool, model_dir: str,
                 interval_s: float = 30.0,
                 ab_replicas: int = 0,
                 drain_timeout_s: float = 30.0,
                 verbose: bool = False):
        self.pool = pool
        self.model_dir = model_dir
        self.interval_s = float(interval_s)
        # A/B canary subset size: 0 = plain rolling reload of the whole
        # pool; k >= 1 = only the first k replicas take the new version
        # (clamped so at least one replica keeps the old version —
        # "canary everything" is just a rolling reload)
        self.ab_replicas = max(0, min(int(ab_replicas),
                                      len(pool.replicas) - 1))
        self.drain_timeout_s = float(drain_timeout_s)
        self.verbose = verbose
        self.reloads = 0               # completed reload sweeps
        self.last_error: str = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # one reload sweep at a time

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReloadWatcher":
        if self.interval_s > 0 and self._thread is None:
            self._stop.clear()        # restartable after stop()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-reload")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # a sweep checks the stop event between replicas, so the
            # worst case to wait out is one poll plus ONE in-progress
            # drain — not a whole fleet's worth of drains
            self._thread.join(timeout=self.interval_s
                              + self.drain_timeout_s + 30)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception as e:    # noqa: BLE001 — watcher must survive
                # a bad poll (transient IO, mid-write races) must not
                # kill the watcher; the next tick retries
                self.last_error = f"{type(e).__name__}: {e}"
                if self.verbose:
                    print(f"serve-reload: poll failed: {self.last_error}",
                          flush=True)

    # -- polling ---------------------------------------------------------
    def _stale(self, target_round: int) -> List[int]:
        """Replica indices the next sweep must update: members of the
        reload scope (canary subset in A/B mode, everyone otherwise)
        not already serving ``target_round``. Keyed on the VERSION, not
        just the round scan, so a sweep that failed partway (one
        replica swapped, the next raised) retries the stragglers on the
        following tick instead of stranding a mixed-version pool."""
        scope = range(self.ab_replicas or len(self.pool.replicas))
        want = version_name(target_round)
        return [i for i in scope
                if self.pool.replicas[i].version != want]

    def check_once(self) -> bool:
        """One poll: returns True when a reload happened. The cheap
        round scan gates the expensive verify+read — steady state costs
        one listdir per tick."""
        latest = ckpt.find_latest(self.model_dir)
        if latest is None or not self._stale(latest[0]):
            return False
        # work to do: verified read (falls back a round on corruption;
        # returns the blob so replicas never re-read)
        valid = ckpt.find_latest_valid(self.model_dir, want_blob=True,
                                       verbose=self.verbose)
        if valid is None:
            return False
        r, path, blob = valid
        stale = self._stale(r)        # the newest file may not have
        if not stale:                 # verified; re-check at the round
            return False              # that actually loaded
        return self.reload_from_blob(blob, path=path, targets=stale) > 0

    def reload_from_blob(self, blob: Dict[str, Any], path: str = "",
                         targets: Optional[List[int]] = None,
                         canary: Optional[bool] = None) -> int:
        """Roll a verified checkpoint blob into the target replicas, one
        at a time with graceful drain; returns how many replicas
        actually swapped. Structure-checked against the first target's
        graph before any replica is touched (every replica shares the
        net config). The sweep re-checks the stop event between
        replicas so teardown never races a long rolling drain — an
        aborted sweep's stragglers are retried by the stale gate on the
        next tick (or finished by the next process), and only a sweep
        that finished every target counts toward ``reloads``.
        ``canary`` labels the ledger events; default = whether this
        watcher's reload scope is a canary subset (promote() passes
        False: promotion converges the fleet, it does not split it)."""
        meta = blob["meta"]
        new_round = int(meta["round"])
        digest = ckpt.blob_digest(meta)
        targets = (self._stale(new_round) if targets is None
                   else list(targets))
        if not targets:
            return 0
        if canary is None:
            canary = bool(self.ab_replicas)
        done = 0
        with self._lock:
            first = self.pool.replicas[targets[0]]
            ckpt.check_structure(
                meta, first.engine.trainer.graph.structure_signature())
            for idx in targets:
                if self._stop.is_set():
                    break
                # each replica's drain+swap runs under its own
                # distributed span: the replica_state transitions and
                # the weights_reload event below inherit the trace
                # context, so tools/trace_assemble.py can attribute a
                # reload-caused latency spike to this exact sweep
                with DISTTRACE.span(
                        "serve.reload", cat="serve",
                        args={"replica": idx, "round": new_round,
                              "digest": digest, "canary": canary}):
                    old_round = self.pool.reload_replica(
                        idx, blob["params"], blob["state"], new_round,
                        digest=digest,
                        drain_timeout_s=self.drain_timeout_s)
                    tp = DISTTRACE.current_traceparent()
                    LEDGER.event(
                        "weights_reload", replica=idx,
                        old_round=old_round, new_round=new_round,
                        digest=digest, path=path, canary=canary,
                        **({"traceparent": tp} if tp else {}))
                done += 1
            if done == len(targets):
                self.reloads += 1
        if self.verbose and done:
            mode = (f"canary x{done}" if canary else f"all x{done}")
            tail = "" if done == len(targets) \
                else f" (aborted; {len(targets) - done} left stale)"
            print(f"serve-reload: {version_name(new_round)} "
                  f"({digest or 'no digest'}) -> {mode} replicas{tail}",
                  flush=True)
        return done

    def promote(self) -> bool:
        """A/B promotion: roll EVERY replica behind the newest valid
        checkpoint forward to it — non-canaries catch up to (or past)
        the canaries, and a canary that itself fell behind a
        just-written round moves too, so promotion cannot lose a race
        against a trainer that kept checkpointing into the same
        model_dir. Returns True when anything moved."""
        valid = ckpt.find_latest_valid(self.model_dir, want_blob=True,
                                       verbose=self.verbose)
        if valid is None:
            return False
        r, path, blob = valid
        want = version_name(r)
        behind = [rep.idx for rep in self.pool.replicas
                  if rep.version != want]
        if not behind:
            return False
        return self.reload_from_blob(blob, path=path, targets=behind,
                                     canary=False) > 0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "model_dir": self.model_dir,
            "interval_s": self.interval_s,
            "ab_replicas": self.ab_replicas,
            "reloads": self.reloads,
            "last_error": self.last_error,
            "running": self._thread is not None
            and self._thread.is_alive(),
        }
