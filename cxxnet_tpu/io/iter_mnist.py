"""MNIST idx-ubyte iterator.

Reference: MNISTIterator (/root/reference/src/io/iter_mnist-inl.hpp:15-165):
reads (optionally gzipped) idx files, optional shuffle, flat (b,1,1,784) or
image mode, yields full batches with zero-copy views; the final partial batch
is padded and marked via num_batch_padd (the reference instead wraps around
when round_batch is on — supported here too).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from .data import DataBatch, DataIter, dist_slice, register_iter
from .stream import open_maybe_gz as _open_maybe_gz_stream


def _open_maybe_gz(path: str):
    # local or remote (gs:// etc), transparently gunzipped
    return _open_maybe_gz_stream(path)


def read_idx(path: str) -> np.ndarray:
    """Parse an idx-ubyte file (images magic 2051, labels magic 2049)."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic % 256
        dims = [struct.unpack(">i", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


@register_iter("mnist")
class MNISTIterator(DataIter):
    supports_dist_shard = True

    def set_param(self, name, val):
        if name == "path_img":
            self.path_img = val
        elif name == "path_label":
            self.path_label = val
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "input_flat":
            self.input_flat = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)
        elif name == "index_offset":
            # base added to instance indices (reference
            # iter_mnist-inl.hpp:33 inst_offset_) — aligns ids with
            # attachtxt side files numbered from a nonzero base
            self.index_offset = int(val)

    def __init__(self, cfg):
        self.path_img = ""
        self.path_label = ""
        self.shuffle = 0
        self.input_flat = 1
        self.batch_size = 128
        self.seed = 0
        self.round_batch = 0
        self.silent = 0
        self.index_offset = 0
        self.nworker = 1
        self.rank = 0
        super().__init__(cfg)

    def init(self):
        images = read_idx(self.path_img).astype(np.float32) / 256.0
        labels = read_idx(self.path_label).astype(np.float32)
        n = images.shape[0]
        if self.input_flat:
            self.images = images.reshape(n, 1, 1, -1)
        else:
            h, w = images.shape[1], images.shape[2]
            self.images = images.reshape(n, h, w, 1)
        self.labels = labels.reshape(n, 1)
        self.inst = np.arange(n, dtype=np.int64) + self.index_offset
        if self.nworker > 1:
            sl = dist_slice(n, self.nworker, self.rank)
            self.images = self.images[sl]
            self.labels = self.labels[sl]
            self.inst = self.inst[sl]    # ids stay global
        self._order = np.arange(self.images.shape[0])
        self._rng = np.random.RandomState(self.seed)
        self.before_first()
        if not self.silent:
            print(f"MNISTIterator: load {n} images, shuffle={self.shuffle}")

    def before_first(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def next(self) -> Optional[DataBatch]:
        n = self.images.shape[0]
        bs = self.batch_size
        if self._pos >= n:
            return None
        idx = self._order[self._pos:self._pos + bs]
        padd = 0
        if len(idx) < bs:
            padd = bs - len(idx)
            if self.round_batch:
                # wrap around for equal-size distributed epochs; wrapped rows
                # still count as padding so loss/metrics exclude the
                # duplicates (reference iter_batch_proc-inl.hpp:85-99 sets
                # num_batch_padd = num_overflow)
                idx = np.concatenate([idx, self._order[:padd]])
            else:
                idx = np.concatenate([idx, np.repeat(idx[-1:], padd)])
        self._pos += bs
        return DataBatch(data=self.images[idx], label=self.labels[idx],
                         num_batch_padd=padd, inst_index=self.inst[idx])
