"""ctypes bridge to the native data-plane library (cxxnet_tpu/native/).

Loads ``libcxxnet_native.so`` if built (cxxnet_tpu/native/build.sh) and
exposes JPEG decode; falls back silently (returning None) so the pure-
Python pipeline keeps working without the native build. ctypes releases
the GIL during calls, so a ThreadPoolExecutor over these decoders gets
real multi-core parallelism — the same design as the reference's OpenMP
decode loop (iter_image_recordio-inl.hpp:206-250).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

_lib = None
_lib_lock = threading.Lock()
_tried = False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    with _lib_lock:
        if _tried:
            return _lib
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native", "libcxxnet_native.so")
        try:
            lib = ctypes.CDLL(path)
            lib.cxn_jpeg_dims.restype = ctypes.c_int
            lib.cxn_jpeg_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.cxn_jpeg_decode.restype = ctypes.c_int
            lib.cxn_jpeg_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            _lib = lib
        except OSError:
            _lib = None
        _tried = True
    return _lib


def available() -> bool:
    return _load() is not None


def try_decode(data: bytes, want_channels: int = 3) -> Optional[np.ndarray]:
    """Decode JPEG bytes to HWC uint8, or None if the native lib is absent
    or the payload is not a JPEG it can handle."""
    lib = _load()
    if lib is None:
        return None
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    if lib.cxn_jpeg_dims(data, len(data), ctypes.byref(h), ctypes.byref(w),
                         ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, want_channels), np.uint8)
    rc = lib.cxn_jpeg_decode(data, len(data), want_channels,
                             out.ctypes.data_as(ctypes.c_void_p),
                             h.value, w.value)
    if rc != 0:
        return None
    return out
