"""Image record iterator: sharded reads + parallel decode + augmentation.

Reference analog: ImageRecordIOIterator + ImageRecordIOParser
(/root/reference/src/io/iter_image_recordio-inl.hpp:92-333) — the modern
imgrec path: dmlc::InputSplit chunked reads sharded by (rank, nworkers),
OpenMP parallel jpeg decode, in-chunk shuffle, ThreadedIter prefetch. Here
the same pipeline is a chunked RecordReader + a thread pool for decode
(optionally the native C++ decoder when built) + numpy augmentation,
wrapped by the generic threadbuffer iterator for prefetch.

Also registers ``imgbin``/``imgbinx``/``imginst``/``imgbinold`` as aliases:
the legacy BinaryPage formats collapse into recordio in this framework
(tools/im2rec converts; see tools/ for the packer).
"""

from __future__ import annotations

import concurrent.futures as futures
import io as _io
import os
from typing import List, Optional

import numpy as np

from .data import DataBatch, DataIter, register_iter
from .recordio import ImageRecord, RecordReader, read_image_list
from .augment import (AugmentParams, ImageAugmenter, MeanStore,
                      mean_cache_path, pack_label)


def decode_image(data: bytes, want_channels: int = 3) -> np.ndarray:
    """Decode jpeg/png bytes to HWC uint8 (RGB, or single-channel luma when
    ``want_channels == 1``) via the native decoder if built, else cv2/PIL.
    Raw float tensors (flag==1 records) skip this."""
    from . import native
    arr = native.try_decode(data, want_channels)
    if arr is not None:
        return arr
    gray = want_channels == 1
    try:
        import cv2
        flag = cv2.IMREAD_GRAYSCALE if gray else cv2.IMREAD_COLOR
        a = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
        if a is None:
            raise ValueError("cv2.imdecode failed")
        return a[:, :, None] if gray else a[:, :, ::-1]      # BGR -> RGB
    except ImportError:
        from PIL import Image
        img = Image.open(_io.BytesIO(data)).convert("L" if gray else "RGB")
        a = np.asarray(img)
        return a[:, :, None] if gray else a


def expand_conf_files(prefix: str, ids: str, rank: int, nworker: int):
    """Expand ``image_conf_prefix``/``image_conf_ids`` into this worker's
    (bin, lst) file pairs (reference iter_thread_imbin_x-inl.hpp:113-150):
    ids is an inclusive range 'lb-ub', each id formats the printf-style
    prefix, and workers take contiguous chunks of ceil(n/nworker) files."""
    import re
    m = re.match(r"^(-?\d+)-(-?\d+)$", ids.strip())
    if not m:
        raise ValueError(
            f"image_conf_ids only supports a range like 1-100, got {ids!r}")
    lb, ub = int(m.group(1)), int(m.group(2))
    n = ub + 1 - lb
    if n <= 0:
        raise ValueError(f"image_conf_ids: empty range {ids!r}")
    # validate the formatting over the FULL id range before worker slicing
    # (a per-worker check could see one name and miss that every worker
    # resolves to the same file)
    try:
        all_names = [prefix % i for i in range(lb, ub + 1)]
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"image_conf_prefix must contain a printf-style integer "
            f"placeholder (e.g. 'part%03d'), got {prefix!r}: {e}") from e
    if n > 1 and len(set(all_names)) != len(all_names):
        raise ValueError(
            f"image_conf_prefix {prefix!r} does not vary with "
            "image_conf_ids — missing a %d placeholder?")
    if nworker > 1:
        step = (n + nworker - 1) // nworker
        begin = min(rank * step, n)
        end = min((rank + 1) * step, n)
        if begin >= end:
            raise ValueError(
                "image_conf: too many workers — the id list cannot be "
                "divided between them")
        all_names = all_names[begin:end]
    return [(name + ".bin", name + ".lst") for name in all_names]


@register_iter("imgrec", "imgbin", "imgbinx", "imginst", "imgbinold")
class ImageRecordIterator(DataIter):
    """Batched, augmented, sharded image-record reader."""

    supports_dist_shard = True

    def set_param(self, name, val):
        if name in ("image_rec", "path_imgrec"):
            self.rec_path = val
        elif name in ("image_bin", "path_imgbin"):
            # legacy BinaryPage pack (reference iter_thread_imbin); labels
            # come from the k-th line of image_list
            self.bin_path = val
        elif name in ("image_list", "path_imglist"):
            self.list_path = val
        elif name == "image_conf_prefix":
            # printf-style template for multi-file BinaryPage packs
            # (reference iter_thread_imbin_x-inl.hpp:113-150): each id in
            # image_conf_ids expands to <prefix%id>.bin/.lst
            self.conf_prefix = val
        elif name == "image_conf_ids":
            self.conf_ids = val
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "input_shape":
            self.input_shape = tuple(int(x) for x in val.split(","))
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)
        elif name == "decode_threads":
            self.nthread = int(val)
        elif name == "silent":
            self.silent = int(val)
        else:
            self.aug.set_param(name, val)

    def __init__(self, cfg):
        self.rec_path = ""
        self.bin_path = ""
        self.list_path = ""
        self.conf_prefix = ""
        self.conf_ids = ""
        self.batch_size = 128
        self.input_shape = None
        self.shuffle = 0
        self.seed = 0
        self.label_width = 1
        self.round_batch = 0
        self.nworker = int(os.environ.get("CXXNET_NUM_WORKER", "1"))
        self.rank = int(os.environ.get("CXXNET_WORKER_RANK",
                                       os.environ.get("PS_RANK", "0")))
        self.nthread = min(8, os.cpu_count() or 4)
        self.silent = 0
        self.aug = AugmentParams()
        super().__init__(cfg)

    # -- setup -------------------------------------------------------------
    def init(self):
        if self.conf_prefix:
            if self.rec_path or self.bin_path or self.list_path:
                raise ValueError(
                    "set either image_conf_prefix or image_bin/image_list, "
                    "not both (reference iter_thread_imbin_x-inl.hpp:124)")
            self._conf_pairs = expand_conf_files(
                self.conf_prefix, self.conf_ids, self.rank, self.nworker)
            if self.round_batch and self.nworker > 1:
                self._check_conf_batch_counts()
        elif not self.rec_path and not self.bin_path:
            raise ValueError("imgrec: image_rec (or image_bin) must be set")
        elif self.round_batch and self.nworker > 1:
            self._check_shard_batch_counts()
        if self.bin_path and not self.list_path:
            raise ValueError("imgbin: image_list must accompany image_bin "
                             "(labels live in the list)")
        if self.input_shape is None:
            raise ValueError("imgrec: input_shape must be set")
        c, y, x = self.input_shape
        self.augmenter = ImageAugmenter(self.aug, (c, y, x))
        self.mean = MeanStore(mean_cache_path(self.aug), (y, x, c))
        self._label_map = None
        self._list_entries = None
        if self.list_path:
            self._list_entries = read_image_list(self.list_path)   # once
            self._label_map = {idx: lab for idx, lab, _
                               in self._list_entries}
        if self.aug.device_normalize == -1:
            # auto-resolve: uint8 H2D (4x smaller transfer + on-device
            # normalize) is the production default whenever it is exact —
            # crop/mirror keep uint8 pixels. Fall back to the host float
            # path for float-producing augmentations (affine/contrast/
            # illumination), raw float-tensor records (flag==1), and
            # images smaller than the crop (the upscale interpolates).
            # The size check samples the shard's first few records (not
            # just one — a large first image must not hide sub-crop-size
            # ones behind it and silently switch the default's numerics);
            # datasets mixing sizes deeper than the probe should set
            # device_normalize=0 explicitly.
            exact = (not self.aug.needs_affine
                     and self.aug.max_random_contrast == 0
                     and self.aug.max_random_illumination == 0)
            if exact:
                for rec in self._peek_records(8):
                    if rec.flag != 0:
                        exact = False
                        break
                    img = self._decode(rec)
                    _, y, x = self.input_shape
                    if img.shape[0] < y or img.shape[1] < x:
                        exact = False
                        break
            self.aug.device_normalize = int(exact)
            if not self.silent:
                print(f"imgrec: device_normalize auto-resolved to "
                      f"{self.aug.device_normalize} "
                      f"({'uint8 device path' if exact else 'host float path'})")
        self._pool = futures.ThreadPoolExecutor(self.nthread)
        self._rng = np.random.RandomState(self.seed + 7 * self.rank)
        # monotonically increasing per-item augmentation counter, hashed
        # before seeding so streams are deterministic under any thread-pool
        # schedule yet uncorrelated across seeds/ranks
        self._item_counter = (self.seed << 32) ^ (self.rank << 56)
        if self.aug.mean_img and not self.mean.ready:
            self._compute_mean()
        self.before_first()


    def _check_conf_batch_counts(self) -> None:
        """Whole-file conf-prefix sharding gives each rank ceil(shard/batch)
        batches; when shards are uneven enough that those counts differ,
        round_batch CANNOT equalize epochs and every jitted update would
        deadlock on a missing rank. Fail fast at init (counting .lst lines
        is cheap and the lists are on the shared filesystem)."""
        counts = []
        for rank in range(self.nworker):
            pairs = expand_conf_files(self.conf_prefix, self.conf_ids,
                                      rank, self.nworker)
            n = sum(len(read_image_list(lst)) for _, lst in pairs)
            counts.append(-(-n // self.batch_size))      # ceil
        if len(set(counts)) != 1:
            raise ValueError(
                "image_conf_prefix + round_batch: per-rank batch counts "
                f"{counts} are unequal — whole-file sharding cannot give "
                "every worker the same epoch length with these pack sizes; "
                "re-pack into equal-size parts (tools/im2bin.py) or use a "
                "single recordio file (byte-range sharded)")

    def _peek_records(self, n: int) -> List[ImageRecord]:
        """First ``n`` records of this worker's shard (fewer for a short
        shard) — init-time probe for the device_normalize auto-resolution."""
        reader = self._reader()
        out: List[ImageRecord] = []
        try:
            for payload in reader:
                out.append(ImageRecord.unpack(payload))
                if len(out) >= n:
                    break
        finally:
            close = getattr(reader, "close", None)
            if close is not None:
                close()
        return out

    def _check_shard_batch_counts(self) -> None:
        """round_batch promises every rank the same number of batches per
        epoch (each rank emits ceil(shard/batch), wrapping its own shard) —
        but byte-range recordio shards and round-robin binpage page shards
        can hold unequal record counts, and if the per-rank ceil counts
        differ every rank's jitted update deadlocks waiting on a missing
        peer. Fail fast at init with a header-only count (payload bytes are
        never read)."""
        if self.bin_path:
            from .binpage import num_pages, page_object_count
            per_page = [page_object_count(self.bin_path, p)
                        for p in range(num_pages(self.bin_path))]
            recs = [sum(per_page[r::self.nworker])
                    for r in range(self.nworker)]
        else:
            from .recordio import shard_record_counts
            recs = shard_record_counts(self.rec_path, self.nworker)
        counts = [-(-n // self.batch_size) for n in recs]      # ceil
        if len(set(counts)) != 1:
            raise ValueError(
                f"round_batch with {self.nworker} workers: per-rank batch "
                f"counts {counts} (record counts {recs}) are unequal — "
                "every rank must emit the same epoch length or distributed "
                "training deadlocks; re-pack with tools/im2rec.py "
                "(uniform record sizes shard evenly) or adjust batch_size")

    def _reader(self):
        """Iterable of packed ImageRecord payloads: recordio, a legacy
        BinaryPage pack re-wrapped on the fly (k-th object pairs with the
        k-th image_list line for inst_id/label), or this worker's slice of
        a multi-file conf-prefix pack set."""
        if self.conf_prefix:
            from .binpage import iter_binpage

            def gen_multi():
                for bin_path, lst_path in self._conf_pairs:
                    entries = read_image_list(lst_path)
                    # file-level partitioning only: each worker owns whole
                    # files, so no intra-file (rank, nworker) split here
                    for obj_idx, data in iter_binpage(bin_path, 0, 1):
                        inst_id, labels, _ = entries[obj_idx]
                        yield ImageRecord(inst_id=inst_id, labels=labels,
                                          data=data).pack()
            return gen_multi()
        if not self.bin_path:
            return RecordReader(self.rec_path, self.rank, self.nworker)
        from .binpage import iter_binpage
        entries = self._list_entries          # parsed once in init()

        def gen():
            for obj_idx, data in iter_binpage(self.bin_path, self.rank,
                                              self.nworker):
                inst_id, labels, _ = entries[obj_idx]
                yield ImageRecord(inst_id=inst_id, labels=labels,
                                  data=data).pack()
        return gen()

    def _compute_mean(self):
        if not self.silent:
            print(f"computing mean image from {self.rec_path} ...")
        rng = np.random.RandomState(0)
        def gen():
            for payload in self._reader():
                rec = ImageRecord.unpack(payload)
                yield self.augmenter.process(
                    self._decode(rec), rng)
        self.mean.compute(gen())

    def _decode(self, rec: ImageRecord) -> np.ndarray:
        c, y, x = self.input_shape
        if rec.flag == 1:    # raw float tensor record
            return np.frombuffer(rec.data, np.float32).reshape(y, x, c)
        return decode_image(rec.data, c)

    # -- iteration ---------------------------------------------------------
    def before_first(self):
        self._iter = iter(self._reader())
        self._buf: List = []
        self._done = False

    @staticmethod
    def _hash_seed(counter: int) -> int:
        """splitmix64-style integer mix so consecutive counters (and
        shifted seed/rank bases) yield uncorrelated RNG streams. Full
        64-bit output — PCG64 takes it whole; the old 31-bit truncation
        (a RandomState seed-range limit) would birthday-collide hundreds
        of item pairs per ImageNet-scale epoch."""
        z = (counter + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return z ^ (z >> 31)

    def _process_one(self, payload: bytes, item_counter: int):
        rec = ImageRecord.unpack(payload)
        # Generator(PCG64) rather than RandomState: ~8x cheaper to build
        # (~23 us vs ~180 us), and one is built per image — RandomState
        # construction alone was ~13% of the host input budget
        rng = np.random.Generator(
            np.random.PCG64(self._hash_seed(item_counter)))
        if self.aug.device_normalize:
            # defer mean/divideby/scale to the device (trainer applies them
            # after a 4x smaller uint8 host->device copy); crop/mirror
            # stay pure uint8 slicing (process_u8 — no float round-trip),
            # float-producing augmentations (affine/contrast/upscale)
            # take the float path and round to the nearest LSB
            decoded = self._decode(rec)
            img = self.augmenter.process_u8(decoded, rng)
            if img is None:
                img = self.augmenter.process(decoded, rng)
                img = np.clip(np.rint(img), 0.0, 255.0).astype(np.uint8)
        else:
            img = self.augmenter.process(self._decode(rec), rng)
            img = self.mean.apply(img, self.aug)
        if self._label_map is not None and rec.inst_id in self._label_map:
            lab = self._label_map[rec.inst_id]
        else:
            lab = rec.labels
        return img, pack_label(lab, self.label_width), rec.inst_id

    def _decode_raw(self, raw):
        """Decode a list of packed payloads on the pool with fresh
        deterministic per-item seeds."""
        seeds = range(self._item_counter, self._item_counter + len(raw))
        self._item_counter += len(raw)
        return list(self._pool.map(self._process_one, raw, seeds))

    def _fill(self, n: int) -> None:
        """Read up to n raw records, decode them on the pool."""
        raw = []
        for payload in self._iter:
            raw.append(payload)
            if len(raw) >= n:
                break
        if len(raw) < n:
            self._done = True
        if self.shuffle:
            self._rng.shuffle(raw)
        self._buf.extend(self._decode_raw(raw))

    def _wrap_fill(self, n: int):
        """Decode the first ``n`` records of this worker's shard again —
        round_batch wraparound (reference iter_batch_proc-inl.hpp:85-99):
        every rank emits ceil(shard/batch) full batches per epoch, with the
        wrapped duplicates counted as padding so loss/metrics exclude them."""
        reader = self._reader()
        raw = []
        try:
            for payload in reader:
                raw.append(payload)
                if len(raw) >= n:
                    break
        finally:
            close = getattr(reader, "close", None)
            if close is not None:
                close()
        return self._decode_raw(raw)

    def next(self) -> Optional[DataBatch]:
        bs = self.batch_size
        if not self._done and len(self._buf) < bs:
            # decode a few batches ahead so shuffle mixes across batches
            self._fill(bs * 4)
        if not self._buf:
            return None
        take = self._buf[:bs]
        self._buf = self._buf[bs:]
        padd = 0
        if len(take) < bs:
            padd = bs - len(take)
            if self.round_batch:
                take = take + self._wrap_fill(padd)
            if len(take) < bs:          # shard smaller than the shortfall
                take = take + [take[-1]] * (bs - len(take))
        data = np.stack([t[0] for t in take])
        label = np.stack([t[1] for t in take])
        index = np.asarray([t[2] for t in take], np.int64)
        norm = None
        if self.aug.device_normalize:
            # same precedence and op order as the host path
            # (MeanStore.apply): mean_value wins over the mean image, then
            # divideby, then scale
            mean = (self.aug.mean_value if self.aug.mean_value is not None
                    else (self.mean.mean if self.mean.ready else None))
            norm = {"mean": mean, "divideby": self.aug.divideby,
                    "scale": self.aug.scale}
        return DataBatch(data=data, label=label, num_batch_padd=padd,
                         inst_index=index, norm=norm)
