"""BinaryPage pack format: the reference's legacy image-pack container.

Byte-compatible with /root/reference/src/utils/io.h:99-172 (``BinaryPage``,
64 MiB fixed pages of int32): word 0 holds the object count N, words
1..N+1 hold cumulative end-offsets (word 1 is 0), and object bytes grow
backward from the END of the page — object k occupies bytes
``[PAGE_BYTES - end[k+1], PAGE_BYTES - end[k+1] + (end[k+1]-end[k]))``.
``tools/im2bin.py`` packs jpegs into this format and ``tools/bin2rec.py``
converts packs to recordio; the imgbin iterator reads packs directly
(labels ride the companion ``.lst`` file, k-th object = k-th list line,
matching the reference's ThreadImagePageIterator contract,
iter_thread_imbin-inl.hpp:17-284).
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

import numpy as np

from .stream import getsize, sopen

PAGE_INTS = 64 << 18
PAGE_BYTES = PAGE_INTS * 4


class BinaryPageWriter:
    """Pack byte objects into fixed 64 MiB pages (reference
    BinaryPage::Push + tools/im2bin.cpp main loop)."""

    def __init__(self, path: str):
        self._f = sopen(path, "wb")
        self._clear()

    def _clear(self) -> None:
        self._objs: List[bytes] = []
        self._data_bytes = 0

    def _free_bytes(self) -> int:
        # mirror reference FreeBytes: (kPageSize - (N + 2)) ints - data
        return (PAGE_INTS - (len(self._objs) + 2)) * 4 - self._data_bytes

    def push(self, data: bytes) -> None:
        if len(data) + 4 > self._free_bytes():
            self.flush_page()
            # re-check against an empty page (reference im2bin.cpp checks
            # the retried Push too): an over-page object must error, never
            # be written out of bounds
            if len(data) + 4 > self._free_bytes():
                raise ValueError(
                    f"object of {len(data)} bytes exceeds the 64MiB page")
        self._objs.append(data)
        self._data_bytes += len(data)

    def flush_page(self) -> None:
        if not self._objs:
            return
        page = bytearray(PAGE_BYTES)
        n = len(self._objs)
        struct.pack_into("<i", page, 0, n)
        end = 0
        for k, obj in enumerate(self._objs):
            end += len(obj)
            struct.pack_into("<i", page, 4 * (k + 2), end)
            page[PAGE_BYTES - end:PAGE_BYTES - end + len(obj)] = obj
        self._f.write(bytes(page))
        self._clear()

    def close(self) -> None:
        self.flush_page()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def page_object_count(path: str, page_idx: int) -> int:
    """Object count of one page without reading the full 64 MiB."""
    with sopen(path, "rb") as f:
        f.seek(page_idx * PAGE_BYTES)
        return struct.unpack("<i", f.read(4))[0]


def num_pages(path: str) -> int:
    size = getsize(path)
    if size % PAGE_BYTES:
        raise ValueError(f"{path}: size {size} is not a whole number of "
                         f"64MiB BinaryPages")
    return size // PAGE_BYTES


def iter_binpage(path: str, part: int = 0, nsplit: int = 1) \
        -> Iterator[Tuple[int, bytes]]:
    """Yield (global_object_index, object_bytes) for this worker's share of
    pages (page-granularity sharding, like the reference's per-worker file
    partitioning)."""
    n_pages = num_pages(path)
    # global start index of each page (cheap header reads)
    counts = [page_object_count(path, p) for p in range(n_pages)]
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int64)
    with sopen(path, "rb") as f:
        for p in range(part, n_pages, nsplit):
            f.seek(p * PAGE_BYTES)
            page = f.read(PAGE_BYTES)
            hdr = np.frombuffer(page, "<i4", counts[p] + 2)
            prev = 0
            for k in range(counts[p]):
                end = int(hdr[k + 2])
                size = end - prev
                yield (int(starts[p] + k),
                       page[PAGE_BYTES - end:PAGE_BYTES - end + size])
                prev = end
