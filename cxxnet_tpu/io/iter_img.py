"""Direct image-file iterator (``img``) and side-feature join (``attachtxt``).

Reference analogs:
  * ImageIterator (/root/reference/src/io/iter_img-inl.hpp:17-138): reads a
    ``.lst`` file (``index<TAB>label...<TAB>relative/path``) and loads each
    image straight from disk (OpenCV imread there; PIL/native decoder here),
    with shuffle and multi-label support. The reference emits DataInst and
    relies on a separate batcher; here batching/augmentation are built in,
    matching this framework's batched iterator protocol.
  * AttachTxtIterator (/root/reference/src/io/iter_attach_txt-inl.hpp:15-101):
    decorator that joins per-instance side features (text file: first token is
    the feature dim, then ``inst_id f_1 .. f_dim`` rows) into
    ``batch.extra_data`` by instance id, feeding the graph's ``in_1..`` extra
    input nodes (nnet_config.h:229-252).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .data import DataBatch, DataIter, register_iter
from .augment import (AugmentParams, ImageAugmenter, MeanStore,
                      mean_cache_path, pack_label)
from .recordio import read_image_list


@register_iter("img")
class ImageIterator(DataIter):
    """Per-file image loader driven by an image list file."""

    def set_param(self, name, val):
        if name in ("image_list", "path_imglist"):
            self.list_path = val
        elif name in ("image_root", "path_imgdir"):
            self.root = val
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "input_shape":
            self.input_shape = tuple(int(x) for x in val.split(","))
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "silent":
            self.silent = int(val)
        else:
            self.aug.set_param(name, val)

    def __init__(self, cfg):
        self.list_path = ""
        self.root = ""
        self.batch_size = 128
        self.input_shape = None
        self.shuffle = 0
        self.seed = 0
        self.label_width = 1
        self.silent = 0
        self.aug = AugmentParams()
        super().__init__(cfg)

    def init(self):
        if not self.list_path:
            raise ValueError("img: image_list must be set")
        if self.input_shape is None:
            raise ValueError("img: input_shape must be set")
        c, y, x = self.input_shape
        self.augmenter = ImageAugmenter(self.aug, (c, y, x))
        self.mean = MeanStore(mean_cache_path(self.aug), (y, x, c))
        self.items = []          # (inst_id, labels, filename)
        for idx, labels, fname in read_image_list(self.list_path):
            self.items.append((idx, labels, fname))
        if not self.silent:
            print(f"ImageIterator: image_list={self.list_path} "
                  f"({len(self.items)} images)")
        self._order = np.arange(len(self.items))
        self._rng = np.random.RandomState(self.seed)
        if self.aug.mean_img and not self.mean.ready:
            rng = np.random.RandomState(0)
            self.mean.compute(self.augmenter.process(self._load(i), rng)
                              for i in range(len(self.items)))
        self.before_first()

    def _load(self, i: int) -> np.ndarray:
        from .iter_imgrec import decode_image
        _, _, fname = self.items[i]
        path = os.path.join(self.root, fname) if self.root else fname
        with open(path, "rb") as f:
            return decode_image(f.read(), self.input_shape[0])

    def before_first(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def next(self) -> Optional[DataBatch]:
        n = len(self.items)
        if self._pos >= n:
            return None
        bs = self.batch_size
        idx = self._order[self._pos:self._pos + bs]
        self._pos += bs
        padd = 0
        if len(idx) < bs:
            padd = bs - len(idx)
            idx = np.concatenate([idx, np.repeat(idx[-1:], padd)])
        imgs, labels, ids = [], [], []
        for i in idx:
            img = self.augmenter.process(self._load(int(i)), self._rng)
            imgs.append(self.mean.apply(img, self.aug))
            labels.append(pack_label(self.items[int(i)][1],
                                     self.label_width))
            ids.append(self.items[int(i)][0])
        return DataBatch(data=np.stack(imgs), label=np.stack(labels),
                         num_batch_padd=padd,
                         inst_index=np.asarray(ids, np.int64))


@register_iter("attachtxt")
class AttachTxtIterator(DataIter):
    """Join per-instance side features into ``batch.extra_data`` by id."""

    def set_param(self, name, val):
        if name == "filename":
            self.filename = val

    def __init__(self, cfg, base: DataIter):
        self.filename = ""
        self.base = base
        super().__init__(cfg)

    def init(self):
        if not self.filename:
            raise ValueError("attachtxt: filename must be set")
        with open(self.filename) as f:
            toks = f.read().split()
        self.dim = int(toks[0])
        self.table = {}
        pos = 1
        while pos < len(toks):
            inst_id = int(toks[pos])
            feat = np.asarray([float(t) for t in toks[pos + 1:pos + 1 + self.dim]],
                              np.float32)
            if feat.shape[0] != self.dim:
                raise ValueError(
                    "attachtxt: data do not match dimension specified")
            self.table[inst_id] = feat
            pos += 1 + self.dim

    def before_first(self):
        self.base.before_first()

    def next(self) -> Optional[DataBatch]:
        b = self.base.next()
        if b is None:
            return None
        if b.inst_index is None:
            raise ValueError("attachtxt: base iterator yields no inst_index")
        extra = np.zeros((b.batch_size, 1, 1, self.dim), np.float32)
        for row, inst_id in enumerate(np.asarray(b.inst_index)):
            feat = self.table.get(int(inst_id))
            if feat is not None:
                extra[row, 0, 0, :] = feat
        b.extra_data = list(b.extra_data) + [extra]
        return b
