"""Processing iterators: batching, threaded prefetch, in-memory cache,
synthetic data, CSV.

Reference analogs:
  * BatchAdaptIterator (iter_batch_proc-inl.hpp:17-129) — instance->batch
    packing with round_batch wraparound and partial-batch padding;
  * ThreadBufferIterator (iter_batch_proc-inl.hpp:132-220) — double-buffered
    producer thread over whole batches, built on utils/thread_buffer.h;
  * DenseBufferIterator (iter_mem_buffer-inl.hpp:17-78) — cache first N
    batches in RAM and loop over them;
  * CSVIterator (iter_csv-inl.hpp:14-112) — label_width leading columns.

The synthetic iterator is this framework's deterministic stand-in for the
examples-as-tests strategy (SURVEY §4): separable gaussian clusters so unit
tests can assert that training actually learns.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import weakref
from typing import List, Optional

import numpy as np

from .data import DataBatch, DataIter, dist_slice, register_iter
from ..telemetry.registry import REGISTRY
from . import iter_mnist  # noqa: F401  (register mnist)

_TB_SEQ = itertools.count()


class _ProducerError:
    """Queue sentinel carrying an exception out of the producer
    thread: a fetch that dies must surface on the CONSUMER side, not
    silently end the producer — a consumer blocked on an unbounded
    ``queue.get`` behind a dead producer is an indefinite hang."""
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


@register_iter("threadbuffer")
class ThreadBufferIterator(DataIter):
    """Background-thread prefetch with a bounded queue. The reference uses a
    semaphore-handshake double buffer (thread_buffer.h:22-205); a queue of
    depth ``buffer_size`` generalizes it (depth 1 == double buffering).

    Telemetry: queue depth rides a per-instance COLLECT-CALLBACK gauge
    (``cxxnet_io_prefetch_queue_depth``, GaugeChild.set_function): the
    depth is read straight off the queue at snapshot/exposition time,
    so a scrape or fleet push can never see a value staler than the
    queue itself — the is-the-input-pipeline-keeping-up signal the
    step-time probe's data-wait EMA corroborates. Each upstream fetch
    lands in the ``cxxnet_io_fetch_latency_seconds`` histogram."""

    def set_param(self, name, val):
        if name == "buffer_size":
            self.buffer_size = int(val)

    def __init__(self, cfg, base: DataIter):
        self.buffer_size = 2
        self.base = base
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        g = REGISTRY.gauge(
            "cxxnet_io_prefetch_queue_depth",
            "Batches buffered ahead by the threadbuffer iterator "
            "(evaluated at read time)",
            labels=("iter",)).labels(str(next(_TB_SEQ)))
        # the callback reads through a weakref: _queue is rebound by
        # before_first (so the LIVE queue is always the one measured),
        # and a discarded iterator — there is no teardown hook — must
        # not stay pinned in the process-global registry along with its
        # queue of buffered batches
        ref = weakref.ref(self)

        def _depth() -> int:
            s = ref()
            q = s._queue if s is not None else None
            return q.qsize() if q is not None else 0
        g.set_function(_depth)
        self._h_fetch = REGISTRY.histogram(
            "cxxnet_io_fetch_latency_seconds",
            "Upstream batch-fetch latency inside the prefetch producer")
        super().__init__(cfg)

    def init(self):
        pass

    def _put(self, item) -> bool:
        """TIMED put re-checking _stop: a plain blocking put deadlocks
        teardown when the queue is full and the consumer has stopped
        draining (the drain-and-join below would wait forever on a
        producer stuck in put) — the PR-1..3 shutdown hang. Returns
        False when teardown interrupted the put."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        try:
            self.base.before_first()
            while not self._stop.is_set():
                t0 = time.perf_counter()
                batch = self.base.next()
                self._h_fetch.observe(time.perf_counter() - t0)
                if not self._put(batch) or batch is None:
                    return
        except BaseException as e:      # noqa: BLE001 — relayed, not eaten
            # the consumer re-raises this from next(); a producer that
            # died silently would leave next() blocked forever
            self._put(_ProducerError(e))

    def _join_producer(self):
        """Signal stop, then DRAIN-AND-JOIN in a loop — one drain pass
        is not enough, because the producer may refill the freed slot
        before it observes _stop (the timed put bounds how long that
        goes on; without it this join could hang)."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            while self._thread.is_alive():
                try:
                    while True:
                        self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.05)

    def before_first(self):
        self._join_producer()
        self._stop.clear()
        self._queue = queue.Queue(maxsize=self.buffer_size)
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="io-threadbuffer")
        self._thread.start()

    def next(self):
        if self._queue is None:
            self.before_first()
        item = self._queue.get()
        if isinstance(item, _ProducerError):
            raise item.exc
        return item

    def close(self):
        """Orderly teardown: stop + join the producer (releasing its
        buffered batches), then close the wrapped base when it can be
        closed — abandoned chains must not leak a spinning producer."""
        self._join_producer()
        base_close = getattr(self.base, "close", None)
        if callable(base_close):
            base_close()


@register_iter("throttle")
class ThrottleIterator(DataIter):
    """Delay every ``next()`` by ``throttle_ms`` — a deterministic
    stand-in for an expensive decode, used by the data-starvation
    drills (doc/tasks.md "Input data service"): a trainer fed through
    a throttled local pipeline goes input-bound, the same trainer fed
    the same batches by a warmed data-service reader does not."""

    def set_param(self, name, val):
        if name == "throttle_ms":
            self.throttle_ms = float(val)

    def __init__(self, cfg, base: DataIter):
        self.throttle_ms = 0.0
        self.base = base
        super().__init__(cfg)

    def before_first(self):
        self.base.before_first()

    def next(self):
        if self.throttle_ms > 0:
            time.sleep(self.throttle_ms / 1e3)
        return self.base.next()

    def close(self):
        # chain-teardown contract: the top iterator's close() must
        # reach a wrapped threadbuffer's producer thread
        base_close = getattr(self.base, "close", None)
        if callable(base_close):
            base_close()


@register_iter("membuffer")
class DenseBufferIterator(DataIter):
    """Cache the first max_buffer batches in RAM, then loop over them."""

    def set_param(self, name, val):
        # max_nbatch is the reference's name (iter_mem_buffer-inl.hpp:27);
        # max_buffer kept as this framework's earlier alias
        if name in ("max_buffer", "max_nbatch"):
            self.max_buffer = int(val)

    def __init__(self, cfg, base: DataIter):
        self.max_buffer = 16
        self.base = base
        self._cache: List[DataBatch] = []
        self._filled = False
        self._pos = 0
        super().__init__(cfg)

    def before_first(self):
        self._pos = 0
        if not self._filled:
            self.base.before_first()
            for _ in range(self.max_buffer):
                b = self.base.next()
                if b is None:
                    break
                self._cache.append(b)
            self._filled = True

    def next(self):
        if self._pos >= len(self._cache):
            return None
        b = self._cache[self._pos]
        self._pos += 1
        return b


@register_iter("csv")
class CSVIterator(DataIter):
    """CSV with label_width leading label columns then features
    (iter_csv-inl.hpp:14-112); optional input_shape to reshape features."""

    supports_dist_shard = True

    def set_param(self, name, val):
        if name == "filename" or name == "path_csv":
            self.filename = val
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "input_shape":
            self.input_shape = tuple(int(x) for x in val.split(","))
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "has_header":
            self.has_header = int(val)
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)

    def __init__(self, cfg):
        self.filename = ""
        self.label_width = 1
        self.batch_size = 128
        self.shuffle = 0
        self.input_shape = None
        self.seed = 0
        self.has_header = 0
        self.nworker = 1
        self.rank = 0
        self._inst_base = 0
        super().__init__(cfg)

    def init(self):
        raw = np.loadtxt(self.filename, delimiter=",", dtype=np.float32,
                         ndmin=2,
                         skiprows=1 if self.has_header else 0)
        self.labels = raw[:, :self.label_width]
        feats = raw[:, self.label_width:]
        n = feats.shape[0]
        if self.input_shape and not (self.input_shape[0] == 1 and
                                     self.input_shape[1] == 1):
            c, y, x = self.input_shape
            self.data = feats.reshape(n, c, y, x).transpose(0, 2, 3, 1).copy()
        else:
            self.data = feats.reshape(n, 1, 1, -1)
        if self.nworker > 1:
            sl = dist_slice(n, self.nworker, self.rank)
            self.data = self.data[sl]
            self.labels = self.labels[sl]
            self._inst_base = sl.start
        self._order = np.arange(self.data.shape[0])
        self._rng = np.random.RandomState(self.seed)
        self.before_first()

    def before_first(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def next(self):
        n = self.data.shape[0]
        bs = self.batch_size
        if self._pos >= n:
            return None
        idx = self._order[self._pos:self._pos + bs]
        padd = 0
        if len(idx) < bs:
            padd = bs - len(idx)
            idx = np.concatenate([idx, np.repeat(idx[-1:], padd)])
        self._pos += bs
        return DataBatch(data=self.data[idx], label=self.labels[idx],
                         num_batch_padd=padd,
                         inst_index=(idx + self._inst_base).astype(np.int64))


class _InMemoryIterator(DataIter):
    """Shared sequential batch cursor over in-memory ``self.data`` /
    ``self.labels`` arrays with tail-padding (num_batch_padd); subclasses
    implement ``init()`` to fill the arrays — generated from
    ``data_gen_seed`` when set, else ``seed_data`` — and call
    ``_finalize_rows()`` afterwards.

    The data service's shard dimension: ``dist_num_worker`` /
    ``dist_worker_rank`` keep only this worker's contiguous row range,
    and when ``data_gen_seed`` is present (service mode: generation
    pinned shard- and epoch-independent) ``seed_data`` only SHUFFLES
    the slice — so the union over shards is exactly one dataset per
    epoch, within-shard order varies per (epoch, shard), and
    ``inst_index`` stays globally unique. That is imgrec's contract:
    data identity from the source, seed_data for ordering."""

    supports_dist_shard = True
    nworker = 1
    rank = 0
    gen_seed = None

    def _finalize_rows(self):
        n = self.data.shape[0]
        self.inst = np.arange(n, dtype=np.int64)
        if self.nworker > 1:
            sl = dist_slice(n, self.nworker, self.rank)
            self.data = self.data[sl]
            self.labels = self.labels[sl]
            self.inst = self.inst[sl]
        if self.gen_seed is not None:
            p = np.random.RandomState(self.seed) \
                .permutation(self.data.shape[0])
            self.data = self.data[p]
            self.labels = self.labels[p]
            self.inst = self.inst[p]

    def before_first(self):
        self._pos = 0

    def next(self):
        n = self.data.shape[0]
        if self._pos >= n:
            return None
        bs = self.batch_size
        idx = np.arange(self._pos, min(self._pos + bs, n))
        padd = 0
        if len(idx) < bs:
            padd = bs - len(idx)
            idx = np.concatenate([idx, np.repeat(idx[-1:], padd)])
        self._pos += bs
        return DataBatch(data=self.data[idx], label=self.labels[idx],
                         num_batch_padd=padd, inst_index=self.inst[idx])


@register_iter("synthetic")
class SyntheticIterator(_InMemoryIterator):
    """Deterministic gaussian-cluster classification data for tests and IO-free
    benchmarking (plays the role of the reference's test_io/test_skipread
    harness, iter_batch_proc-inl.hpp:21,69)."""

    def set_param(self, name, val):
        if name == "num_inst":
            self.num_inst = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "num_class":
            self.num_class = int(val)
        elif name == "input_shape":
            self.input_shape = tuple(int(x) for x in val.split(","))
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)
        elif name == "data_gen_seed":
            self.gen_seed = int(val)

    def __init__(self, cfg):
        self.num_inst = 512
        self.batch_size = 128
        self.num_class = 10
        self.input_shape = (1, 1, 32)
        self.seed = 7
        self.label_width = 1
        super().__init__(cfg)

    def init(self):
        rng = np.random.RandomState(
            self.seed if self.gen_seed is None else self.gen_seed)
        c, y, x = self.input_shape
        dim = c * y * x
        centers = rng.randn(self.num_class, dim).astype(np.float32) * 2.0
        lab = rng.randint(0, self.num_class, size=self.num_inst)
        feats = centers[lab] + 0.5 * rng.randn(self.num_inst, dim).astype(np.float32)
        if c == 1 and y == 1:
            self.data = feats.reshape(self.num_inst, 1, 1, x)
        else:
            self.data = feats.reshape(self.num_inst, c, y, x) \
                .transpose(0, 2, 3, 1).copy()
        self.labels = np.tile(lab.astype(np.float32)[:, None],
                              (1, self.label_width))
        self._finalize_rows()
        self.before_first()


@register_iter("synthetic_lm")
class SyntheticLMIterator(_InMemoryIterator):
    """Deterministic token-sequence data for language-model tests: labels are
    ``(token_t + token_0) mod vocab_size`` — solvable only by attending back
    to position 0, so it exercises attention, not just the FFN. Extension
    iterator (the reference has no sequence data)."""

    def set_param(self, name, val):
        if name == "num_inst":
            self.num_inst = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "vocab_size":
            self.vocab_size = int(val)
        elif name == "seq_len":
            self.seq_len = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "lm_task":
            if val not in ("add0", "copy"):
                raise ValueError(f"unknown lm_task {val!r}")
            self.lm_task = val
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)
        elif name == "data_gen_seed":
            self.gen_seed = int(val)

    def __init__(self, cfg):
        self.num_inst = 512
        self.batch_size = 32
        self.vocab_size = 32
        self.seq_len = 64
        self.seed = 11
        self.lm_task = "add0"
        super().__init__(cfg)

    def init(self):
        rng = np.random.RandomState(
            self.seed if self.gen_seed is None else self.gen_seed)
        toks = rng.randint(0, self.vocab_size,
                           size=(self.num_inst, self.seq_len))
        if self.lm_task == "copy":      # fast-learnable (no attention needed)
            lab = toks
        else:                           # requires attending to position 0
            lab = (toks + toks[:, :1]) % self.vocab_size
        self.data = toks.astype(np.float32) \
            .reshape(self.num_inst, 1, 1, self.seq_len)
        self.labels = lab.astype(np.float32)
        self._finalize_rows()
        self.before_first()
