"""Filesystem stream seam: local paths and remote URLs behind one API.

Reference analog: dmlc Stream, which gives the reference transparent
``hdfs://``/``s3://`` reads and writes for data files and checkpoints
(reference make/config.mk USE_HDFS/USE_S3; Makefile links libdfs). Here
any ``scheme://`` path routes through fsspec — for a TPU framework the
one that matters is ``gs://``, but s3/hdfs/http/memory all ride the same
seam. Local paths keep using plain ``open`` (no fsspec import cost).

Used by: recordio readers/writers, BinaryPage packs, the mnist idx
reader, config files, and checkpoint save/load/auto-resume.

Resilience: every remote operation retries with exponential backoff +
jitter (resilience.retry — one transient object-store 503 must not
abort a training run), and the ``io.open`` / ``io.read`` / ``io.write``
failpoints inject deterministic faults for chaos tests. When any
``io.*`` failpoint is armed, LOCAL operations route through the same
retry/wrapper path so the failure machinery is testable without an
object store.
"""

from __future__ import annotations

import gzip
import itertools
import os
import re
from typing import Callable, List, Optional

from ..config import RetryPolicy
from ..resilience import failpoints, retry_call

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")

# module-level retry policy: main.py overrides from the io_retry_* config
# keys; library users call set_retry_policy directly
_RETRY = RetryPolicy()


def set_retry_policy(policy: RetryPolicy) -> None:
    global _RETRY
    _RETRY = policy


def _with_retry(fn: Callable, what: str, path: str):
    """Retry remote ops (and local ops while io.* failpoints are armed —
    chaos tests need the retry path without an object store); plain
    local ops run bare, zero overhead."""
    if is_remote(path) or failpoints.armed_prefix("io."):
        return retry_call(fn, what=what, attempts=_RETRY.attempts,
                          base_delay_s=_RETRY.base_delay_s,
                          max_delay_s=_RETRY.max_delay_s,
                          jitter=_RETRY.jitter)
    return fn()


class _FailpointFile:
    """read()-path proxy consulted only while ``io.read`` is armed."""

    def __init__(self, f):
        self._f = f

    def read(self, *a):
        failpoints.check("io.read", IOError)
        return self._f.read(*a)

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()

    def __iter__(self):
        return iter(self._f)


def is_remote(path: str) -> bool:
    return bool(_SCHEME_RE.match(path))


def _fs(path: str):
    import fsspec
    return fsspec.core.url_to_fs(path)


def _open_raw(path: str, mode: str):
    """One open attempt, no retry — the primitive both sopen and the
    composite retried operations build on (wrapping sopen itself inside
    another _with_retry would multiply the configured attempts)."""
    failpoints.check("io.open", IOError)
    if is_remote(path):
        import fsspec
        return fsspec.open(path, mode).open()
    return open(path, mode)


def sopen(path: str, mode: str = "rb"):
    """Open a local path or a remote URL as a file object."""
    f = _with_retry(lambda: _open_raw(path, mode), f"open {path}", path)
    if "r" in mode and failpoints.armed("io.read"):
        return _FailpointFile(f)
    return f


def read_bytes(path: str) -> bytes:
    """Whole-object read with ONE retry loop around the open+read pair:
    a read that dies mid-stream cannot be resumed transparently, but
    re-reading the object can — this is what checkpoint loads use for
    remote (or failpoint-armed) paths."""
    def _read():
        with _open_raw(path, "rb") as f:
            failpoints.check("io.read", IOError)
            return f.read()
    return _with_retry(_read, f"read {path}", path)


def open_maybe_gz(path: str):
    """Binary read stream, transparently gunzipped for ``.gz`` paths."""
    if path.endswith(".gz"):
        return gzip.GzipFile(fileobj=sopen(path, "rb"))
    return sopen(path, "rb")


def getsize(path: str) -> int:
    if is_remote(path):
        fs, key = _fs(path)
        return _with_retry(lambda: fs.size(key), f"size {path}", path)
    return os.path.getsize(path)


def exists(path: str) -> bool:
    if is_remote(path):
        fs, key = _fs(path)
        return _with_retry(lambda: fs.exists(key), f"exists {path}", path)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    if is_remote(path):
        fs, key = _fs(path)
        return _with_retry(lambda: fs.isdir(key), f"isdir {path}", path)
    return os.path.isdir(path)


def listdir(path: str) -> List[str]:
    """Basenames of a directory's entries."""
    if is_remote(path):
        fs, key = _fs(path)
        names = _with_retry(lambda: fs.ls(key, detail=False),
                            f"ls {path}", path)
        return [str(n).rstrip("/").rsplit("/", 1)[-1] for n in names]
    return os.listdir(path)


def makedirs(path: str) -> None:
    if is_remote(path):
        fs, key = _fs(path)
        _with_retry(lambda: fs.makedirs(key, exist_ok=True),
                    f"makedirs {path}", path)
    else:
        os.makedirs(path, exist_ok=True)


def remove(path: str) -> None:
    """Delete one file/object (checkpoint rotation, tmp-orphan sweep)."""
    if is_remote(path):
        fs, key = _fs(path)
        _with_retry(lambda: fs.rm(key), f"rm {path}", path)
    else:
        os.remove(path)


def getmtime(path: str) -> float:
    """Last-modified time as a unix timestamp (the tmp-orphan sweep's
    age check). Raises OSError when the backend cannot answer."""
    if is_remote(path):
        fs, key = _fs(path)
        mt = _with_retry(lambda: fs.modified(key), f"mtime {path}", path)
        return mt.timestamp() if hasattr(mt, "timestamp") else float(mt)
    return os.path.getmtime(path)


#: per-process monotonic counter for tmp-file names (see
#: write_bytes_atomic — pid alone does not separate threads)
_TMP_SEQ = itertools.count()

def is_own_tmp(filename: str) -> bool:
    """Whether a directory entry (basename or full path — the shard-set
    sweep walks round subdirectories) is a tmp file of THIS process —
    ``<name>.tmp.<pid>`` (legacy, pre-thread-unique) or
    ``<name>.tmp.<pid>.<seq>``. The orphan sweeps
    (checkpoint.find_latest_valid) must never delete them — an async
    save thread may be mid-write on a blob OR on one of its shard
    files; only the protocol owner here knows the naming scheme.
    Compiled per call so a forked child never reuses its parent's
    pid."""
    return re.search(r"\.tmp\.%d(\.\d+)?$" % os.getpid(),
                     os.path.basename(filename)) is not None


def write_bytes_atomic(path: str, data: bytes) -> None:
    """Atomic-where-possible write: local files go through tmp+fsync+
    rename so a crash never leaves a torn OR silently-unsynced
    checkpoint; object stores are already all-or-nothing per PUT, so
    remote URLs write directly (with retry)."""
    if is_remote(path):
        def _put():
            with _open_raw(path, "wb") as f:
                failpoints.check("io.write", IOError)
                f.write(data)
        _with_retry(_put, f"write {path}", path)
        return
    # pid+sequence-unique tmp name: two writers racing the same target
    # (multi-host misconfig, a retried save overlapping a stuck one, or
    # two THREADS of one process — fleet-snapshot pusher vs round-
    # boundary push, async save vs driver save) must not clobber each
    # other's tmp mid-write; each renames its own file and os.replace
    # keeps the LAST completed write
    tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_SEQ)}"
    with open(tmp, "wb") as f:
        f.write(data)
        # flush + fsync BEFORE the rename: os.replace orders the name
        # change, not the data — after a power cut an unfsynced rename
        # can surface as the new name holding truncated bytes
        f.flush()
        os.fsync(f.fileno())
    # the crash window the resume sweep exists for: a writer dying here
    # leaves a *.tmp.<pid> orphan beside intact older checkpoints
    failpoints.check("io.write", IOError)
    os.replace(tmp, path)
    # fsync the directory so the rename itself is durable
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass          # non-POSIX dir handles (or exotic fs): best effort
