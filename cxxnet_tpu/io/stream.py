"""Filesystem stream seam: local paths and remote URLs behind one API.

Reference analog: dmlc Stream, which gives the reference transparent
``hdfs://``/``s3://`` reads and writes for data files and checkpoints
(reference make/config.mk USE_HDFS/USE_S3; Makefile links libdfs). Here
any ``scheme://`` path routes through fsspec — for a TPU framework the
one that matters is ``gs://``, but s3/hdfs/http/memory all ride the same
seam. Local paths keep using plain ``open`` (no fsspec import cost).

Used by: recordio readers/writers, BinaryPage packs, the mnist idx
reader, config files, and checkpoint save/load/auto-resume.
"""

from __future__ import annotations

import gzip
import os
import re
from typing import List, Optional

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")


def is_remote(path: str) -> bool:
    return bool(_SCHEME_RE.match(path))


def _fs(path: str):
    import fsspec
    return fsspec.core.url_to_fs(path)


def sopen(path: str, mode: str = "rb"):
    """Open a local path or a remote URL as a file object."""
    if is_remote(path):
        import fsspec
        return fsspec.open(path, mode).open()
    return open(path, mode)


def open_maybe_gz(path: str):
    """Binary read stream, transparently gunzipped for ``.gz`` paths."""
    if path.endswith(".gz"):
        return gzip.GzipFile(fileobj=sopen(path, "rb"))
    return sopen(path, "rb")


def getsize(path: str) -> int:
    if is_remote(path):
        fs, key = _fs(path)
        return fs.size(key)
    return os.path.getsize(path)


def exists(path: str) -> bool:
    if is_remote(path):
        fs, key = _fs(path)
        return fs.exists(key)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    if is_remote(path):
        fs, key = _fs(path)
        return fs.isdir(key)
    return os.path.isdir(path)


def listdir(path: str) -> List[str]:
    """Basenames of a directory's entries."""
    if is_remote(path):
        fs, key = _fs(path)
        names = fs.ls(key, detail=False)
        return [str(n).rstrip("/").rsplit("/", 1)[-1] for n in names]
    return os.listdir(path)


def makedirs(path: str) -> None:
    if is_remote(path):
        fs, key = _fs(path)
        fs.makedirs(key, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def write_bytes_atomic(path: str, data: bytes) -> None:
    """Atomic-where-possible write: local files go through tmp+rename so a
    crash never leaves a torn checkpoint; object stores are already
    all-or-nothing per PUT, so remote URLs write directly."""
    if is_remote(path):
        with sopen(path, "wb") as f:
            f.write(data)
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
