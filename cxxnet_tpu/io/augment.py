"""Image augmentation pipeline (host-side, per-instance).

Reference analogs:
  * ImageAugmenter (/root/reference/src/io/image_augmenter-inl.hpp:13-224):
    OpenCV affine pipeline — rotation (max_rotate_angle / rotate_list /
    fixed ``rotate``), shear, aspect-ratio jitter, random scale
    (min/max_random_scale), random/center crop to (y,x), mirror, fill_value.
  * AugmentIterator (/root/reference/src/io/iter_augment_proc-inl.hpp:22-254):
    crop offsets (rand vs center vs fixed crop_y_start/crop_x_start), mirror,
    ``divideby`` scaling, mean-image subtraction with on-the-fly computation
    and caching, mean_value RGB, max_random_contrast / max_random_illumination.

Arrays are float32 HWC (RGB). cv2 is used when an affine transform is
actually requested; the plain crop/mirror path is pure numpy so the common
case has no cv2 dependency.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple, Union

import numpy as np

# either numpy RNG API (see _ri): the per-item decode rng is a
# Generator(PCG64); long-lived callers still pass RandomState
RngLike = Union[np.random.Generator, np.random.RandomState]


class AugmentParams:
    """Parsed augmentation settings; names match the reference config keys."""

    def __init__(self) -> None:
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.max_rotate_angle = 0.0
        self.max_aspect_ratio = 0.0
        self.max_shear_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.min_random_scale = 1.0
        self.max_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.rotate = -1
        self.rotate_list: Sequence[int] = ()
        self.fill_value = 255
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        self.mean_value: Optional[np.ndarray] = None    # (3,) RGB
        self.mean_img: str = ""
        self.divideby = 1.0
        # -1 = auto (imgrec resolves to 1 when the augmentation chain is
        # uint8-exact — crop/mirror only — and records hold encoded images;
        # see ImageRecordIterator.init). 0/1 are explicit off/on.
        self.device_normalize = -1
        self.scale = 1.0

    def set_param(self, name: str, val: str) -> bool:
        if name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "rand_mirror":
            self.rand_mirror = int(val)
        elif name == "mirror":
            self.mirror = int(val)
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        elif name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        elif name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        elif name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        elif name == "min_crop_size":
            self.min_crop_size = int(val)
        elif name == "max_crop_size":
            self.max_crop_size = int(val)
        elif name == "min_random_scale":
            self.min_random_scale = float(val)
        elif name == "max_random_scale":
            self.max_random_scale = float(val)
        elif name == "min_img_size":
            self.min_img_size = float(val)
        elif name == "max_img_size":
            self.max_img_size = float(val)
        elif name == "rotate":
            self.rotate = int(val)
        elif name == "rotate_list":
            self.rotate_list = [int(x) for x in val.split(",") if x]
        elif name == "fill_value":
            self.fill_value = int(val)
        elif name == "max_random_contrast":
            self.max_random_contrast = float(val)
        elif name == "max_random_illumination":
            self.max_random_illumination = float(val)
        elif name == "image_mean":
            self.mean_img = val
        elif name == "mean_value":
            self.mean_value = np.asarray(
                [float(x) for x in val.split(",")], np.float32)
        elif name == "divideby":
            self.divideby = float(val)
        elif name == "device_normalize":
            self.device_normalize = int(val)
        elif name == "scale":
            self.scale = float(val)
        else:
            return False
        return True

    @property
    def needs_affine(self) -> bool:
        return (self.max_rotate_angle > 0 or self.max_shear_ratio > 0
                or self.rotate > 0 or len(self.rotate_list) > 0
                or self.max_aspect_ratio > 0
                or self.min_crop_size > 0
                or self.min_random_scale != 1.0
                or self.max_random_scale != 1.0
                or self.min_img_size > 0
                or self.max_img_size < 1e10)


def mean_cache_path(p: AugmentParams) -> str:
    """Path of the cached mean image (.npy suffix appended when absent;
    ``.binaryproto`` paths pass through — Caffe mean import)."""
    path = p.mean_img
    if path and not path.endswith((".npy", ".binaryproto")):
        path = path + ".npy"
    return path


def load_binaryproto_mean(data: bytes, rgb_flip: bool = True) -> np.ndarray:
    """Parse a Caffe ``mean.binaryproto`` (a serialized BlobProto) into
    an (H, W, C) float32 RGB mean image — the classic ImageNet
    preprocessing artifact (reference tools/caffe_converter). Wire-level
    protobuf parsing via the repo's shared minimal reader
    (telemetry.traceparse.iter_fields) — no Caffe/protobuf dependency.
    Caffe blobs are NCHW with BGR channel order; ``rgb_flip`` (default)
    reverses the channel axis so the result matches this framework's
    RGB pipeline.

    BlobProto fields used: legacy dims num=1 channels=2 height=3
    width=4, payload ``data`` (repeated float, field 5, packed or not),
    new-style ``shape`` (field 7: BlobShape{repeated int64 dim=1})."""
    from ..telemetry.traceparse import iter_fields, read_varint

    legacy = {1: 0, 2: 0, 3: 0, 4: 0}
    shape: list = []
    chunks: list = []
    for field, wt, val in iter_fields(data):
        if wt == 0 and field in legacy:
            legacy[field] = val
        elif field == 5 and wt == 5:            # unpacked float
            chunks.append(np.frombuffer(val, "<f4"))
        elif field == 5 and wt == 2:            # packed floats
            chunks.append(np.frombuffer(val, "<f4"))
        elif field == 7 and wt == 2:            # BlobShape
            for f2, wt2, v2 in iter_fields(val):
                if f2 != 1:
                    continue
                if wt2 == 0:
                    shape.append(v2)
                elif wt2 == 2:                  # packed dims
                    p = 0
                    while p < len(v2):
                        d, p = read_varint(v2, p)
                        shape.append(d)
    arr = (np.concatenate(chunks) if chunks
           else np.zeros((0,), np.float32))
    if not shape:
        shape = [d for d in (legacy[1], legacy[2], legacy[3], legacy[4])
                 if d]
    if not shape or int(np.prod(shape)) != arr.size:
        raise ValueError(
            f"binaryproto: shape {shape} does not match {arr.size} floats")
    arr = arr.reshape(shape)
    while arr.ndim > 3 and arr.shape[0] == 1:   # (1,C,H,W) -> (C,H,W)
        arr = arr[0]
    if arr.ndim != 3:
        raise ValueError(f"binaryproto: expected a CHW mean, got "
                         f"{arr.shape}")
    arr = np.transpose(arr, (1, 2, 0))          # CHW -> HWC
    if rgb_flip and arr.shape[-1] == 3:
        arr = arr[:, :, ::-1]                   # BGR -> RGB
    return np.ascontiguousarray(arr, np.float32)


def _center_crop_mean(mean: np.ndarray,
                      shape_hwc: Tuple[int, int, int]) -> np.ndarray:
    """Caffe means are usually computed at the resize size (e.g.
    256x256) while this pipeline subtracts post-crop (e.g. 224x224):
    center-crop the imported mean to the input shape — the standard
    Caffe deploy-time treatment of the mean blob."""
    h, w, c = shape_hwc
    mh, mw = mean.shape[:2]
    if (mh, mw) == (h, w):
        return mean
    if mh < h or mw < w or mean.shape[2] != c:
        raise ValueError(
            f"mean image {mean.shape} incompatible with input "
            f"({h}, {w}, {c}); it must be at least the crop size")
    y0, x0 = (mh - h) // 2, (mw - w) // 2
    return np.ascontiguousarray(mean[y0:y0 + h, x0:x0 + w])


def pack_label(labels, width: int) -> np.ndarray:
    """Zero-pad/truncate a label vector to ``label_width`` entries."""
    out = np.zeros((width,), np.float32)
    w = min(width, len(labels))
    out[:w] = labels[:w]
    return out


def _ri(rng, *args):
    """randint across both numpy RNG APIs: the per-item decode rng is a
    ``np.random.Generator`` (PCG64 — ~8x cheaper to construct per item
    than RandomState, which costs ~0.18 ms each at one per image), while
    long-lived callers (iter_img, mean computation) still pass
    RandomState. Same [lo, hi) semantics on both."""
    f = getattr(rng, "integers", None)
    return f(*args) if f is not None else rng.randint(*args)


class ImageAugmenter:
    """Affine + crop + photometric augmentation of one HWC float image."""

    def __init__(self, p: AugmentParams, out_shape: Tuple[int, int, int]):
        self.p = p
        self.out_c, self.out_y, self.out_x = out_shape

    def _affine(self, img: np.ndarray, rng: RngLike) -> np.ndarray:
        import cv2
        p = self.p
        if p.rotate_list:
            angle = float(p.rotate_list[_ri(rng, len(p.rotate_list))])
        elif p.rotate >= 0:
            angle = float(p.rotate)
        else:
            angle = rng.uniform(-p.max_rotate_angle, p.max_rotate_angle)
        a = angle * np.pi / 180.0
        # aspect/shear jitter on top of rotation (image_augmenter-inl.hpp:70-150)
        ratio = 1.0 + rng.uniform(-p.max_aspect_ratio, p.max_aspect_ratio) \
            if p.max_aspect_ratio > 0 else 1.0
        shear = rng.uniform(-p.max_shear_ratio, p.max_shear_ratio) \
            if p.max_shear_ratio > 0 else 0.0
        h, w = img.shape[:2]
        if p.min_crop_size > 0 and p.max_crop_size + 1 > p.min_crop_size:
            crop = _ri(rng, p.min_crop_size, p.max_crop_size + 1)
            scale = float(self.out_y) / crop
        else:
            scale = rng.uniform(p.min_random_scale, p.max_random_scale)
        # Bound the effective content scale so the scaled image size stays in
        # [min_img_size, max_img_size]. Intentional semantic difference from
        # the reference (image_augmenter-inl.hpp:92-94), which clamps the
        # warp canvas size while keeping content scale: here the affine
        # renders straight into the output crop, so the size bound is
        # expressed as a scale bound instead.
        hscale = np.clip(scale * h, p.min_img_size, p.max_img_size) / h
        wscale = np.clip(scale * w, p.min_img_size, p.max_img_size) / w
        hs, ws = hscale * ratio, wscale / max(ratio, 1e-8)
        cos_a, sin_a = np.cos(a), np.sin(a)
        m = np.array([[cos_a * ws, (sin_a + shear) * hs, 0.0],
                      [-sin_a * ws, (cos_a + shear) * hs, 0.0]], np.float32)
        m[0, 2] = self.out_x / 2.0 - (m[0, 0] * w / 2.0 + m[0, 1] * h / 2.0)
        m[1, 2] = self.out_y / 2.0 - (m[1, 0] * w / 2.0 + m[1, 1] * h / 2.0)
        fv = float(self.p.fill_value)
        return cv2.warpAffine(
            img, m, (self.out_x, self.out_y), flags=cv2.INTER_LINEAR,
            borderMode=cv2.BORDER_CONSTANT, borderValue=(fv, fv, fv))

    def _crop(self, img: np.ndarray, rng: RngLike) -> np.ndarray:
        """Random/center/fixed crop to (out_y, out_x)
        (iter_augment_proc-inl.hpp:60-140)."""
        h, w = img.shape[:2]
        oy, ox = self.out_y, self.out_x
        if h == oy and w == ox:
            return img
        if h < oy or w < ox:     # upscale small images to cover the crop
            import cv2
            s = max(oy / h, ox / w)
            img = cv2.resize(img, (max(ox, int(w * s + 0.5)),
                                   max(oy, int(h * s + 0.5))),
                             interpolation=cv2.INTER_LINEAR)
            h, w = img.shape[:2]
        p = self.p
        if p.rand_crop:
            y0 = _ri(rng, 0, h - oy + 1)
            x0 = _ri(rng, 0, w - ox + 1)
        elif p.crop_y_start >= 0 or p.crop_x_start >= 0:
            y0 = max(p.crop_y_start, 0)
            x0 = max(p.crop_x_start, 0)
        else:
            y0, x0 = (h - oy) // 2, (w - ox) // 2
        return img[y0:y0 + oy, x0:x0 + ox]

    def process_u8(self, img: np.ndarray,
                   rng: RngLike):
        """uint8-exact fast path for the device_normalize pipeline:
        crop + mirror without the float32 round-trip (process() costs
        five full-image passes — float cast, contiguous copy, rint,
        clip, uint8 cast — ~0.5 ms/img of the 1-core host budget;
        crop/mirror are pure slicing on uint8). Returns None when the
        image needs the float path (affine/contrast/illumination
        configured, non-uint8 input, or an upscale — whose float
        interpolation must round exactly like process()+rint); RNG draw
        order matches process() exactly, so falling between paths never
        shifts the augmentation stream."""
        if (self.p.needs_affine or self.p.max_random_contrast > 0
                or self.p.max_random_illumination > 0
                or img.dtype != np.uint8):
            return None
        if img.ndim == 2:
            img = img[:, :, None]
        if img.shape[0] < self.out_y or img.shape[1] < self.out_x:
            return None                       # resize: float path rounds
        cropped = self._crop(img, rng)
        if (self.p.rand_mirror and _ri(rng, 2)) or self.p.mirror:
            cropped = cropped[:, ::-1]
        if img.nbytes > 2 * cropped.nbytes:
            # a view would pin the full decoded image in the ~4x-batch
            # item buffer; copy when the crop keeps only a fraction of it
            return np.ascontiguousarray(cropped)
        # near-full-frame crop: return the VIEW — the batch assembler's
        # np.stack makes the one contiguous copy, and a per-image
        # ascontiguousarray here would double the copies (~0.2 ms/img)
        return cropped

    def process(self, img: np.ndarray,
                rng: RngLike) -> np.ndarray:
        """HWC uint8/float in, (out_y, out_x, C) float32 out (pre-mean)."""
        img = np.asarray(img, np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.p.needs_affine:
            img = self._affine(img, rng)
            if img.ndim == 2:
                img = img[:, :, None]
        img = self._crop(img, rng)
        if (self.p.rand_mirror and _ri(rng, 2)) or self.p.mirror:
            img = img[:, ::-1]
        p = self.p
        if p.max_random_contrast > 0 or p.max_random_illumination > 0:
            c = 1.0 + rng.uniform(-p.max_random_contrast,
                                  p.max_random_contrast)
            b = rng.uniform(-p.max_random_illumination,
                            p.max_random_illumination)
            img = img * c + b
        return np.ascontiguousarray(img, np.float32)


class MeanStore:
    """Mean-image subtraction with on-the-fly computation + .npy caching
    (reference CreateMeanImg, iter_augment_proc-inl.hpp:175-205; the cache
    format here is numpy's, not mshadow's)."""

    def __init__(self, path: str, shape_hwc: Tuple[int, int, int]):
        self.path = path
        self.shape = shape_hwc
        self.mean: Optional[np.ndarray] = None
        from . import stream
        if path and stream.exists(path):
            if path.endswith(".binaryproto"):
                # Caffe mean import (VERDICT r5 #6): parse the BlobProto
                # at the wire level, BGR->RGB, center-crop the (usually
                # resize-sized) mean to the input crop
                with stream.sopen(path, "rb") as f:
                    mean = load_binaryproto_mean(f.read())
                self.mean = _center_crop_mean(mean, shape_hwc)
            else:
                with stream.sopen(path, "rb") as f:
                    self.mean = np.load(f)

    @property
    def ready(self) -> bool:
        return self.mean is not None

    def compute(self, images) -> None:
        """images: iterable of (out_y, out_x, c) float arrays."""
        if self.path.endswith(".binaryproto"):
            raise ValueError(
                f"mean file {self.path!r} not found; .binaryproto means "
                "are imported, never computed — convert with "
                "tools/import_caffe.py --mean or point image_mean at a "
                ".npy path")
        acc = np.zeros(self.shape, np.float64)
        n = 0
        for im in images:
            acc += im
            n += 1
        self.mean = (acc / max(n, 1)).astype(np.float32)
        if self.path:
            from . import stream
            with stream.sopen(self.path, "wb") as f:
                np.save(f, self.mean)

    def apply(self, img: np.ndarray, p: AugmentParams) -> np.ndarray:
        if p.mean_value is not None:
            img = img - p.mean_value
        elif self.mean is not None:
            img = img - self.mean
        if p.divideby != 1.0:
            img = img * (1.0 / p.divideby)
        if p.scale != 1.0:
            img = img * p.scale
        return img
