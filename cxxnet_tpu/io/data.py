"""Data iterator protocol and factory.

Reference: IIterator<DataBatch>/DataInst/DataBatch
(/root/reference/src/io/data.h:19-183) and the config-ordered iterator
chain factory (data.cpp:27-94). Batches are host numpy arrays in NHWC (flat
nodes (n,1,1,k)); ``num_batch_padd`` marks trailing padded rows of the final
partial batch so XLA always sees static shapes and metrics/losses mask the
padding (SURVEY §7 "dynamic batch tail").
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Type

import numpy as np

from ..config import ConfigPairs


@dataclasses.dataclass
class DataBatch:
    data: np.ndarray                      # (batch, y, x, c) or (batch,1,1,n)
    label: np.ndarray                     # (batch, label_width) float32
    num_batch_padd: int = 0               # trailing rows that are padding
    inst_index: Optional[np.ndarray] = None  # (batch,) instance ids
    extra_data: List[np.ndarray] = dataclasses.field(default_factory=list)
    # device_normalize=1 pipelines: data is uint8 and this carries the
    # deferred normalization {"mean": (3,)|(y,x,c)|None, "divideby": f}
    # for the trainer to apply on-device after the (4x smaller) H2D copy
    norm: Optional[dict] = None
    # batches staged on-device (Trainer.stage_batch) keep the host label
    # here: metrics index labels host-side, and in multi-host runs the
    # staged device label spans non-addressable shards
    host_label: Optional[np.ndarray] = None

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class DataIter:
    """Iterator protocol (reference IIterator, data.h:19-39)."""

    #: True on SOURCE iterators that honor dist_num_worker /
    #: dist_worker_rank (serve a 1/nworker row slice). Declared on the
    #: implementing class so the data service's shardability check can
    #: never drift from the code: dist_shardable_sources() derives the
    #: allowed set from the registry.
    supports_dist_shard = False

    def __init__(self, cfg: ConfigPairs):
        self.cfg = cfg
        for k, v in cfg:
            self.set_param(k, v)

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[DataBatch]:
        """Return the next batch or None at end of epoch."""
        raise NotImplementedError

    def __iter__(self):
        self.before_first()
        while True:
            b = self.next()
            if b is None:
                return
            yield b


def close_chain(it) -> None:
    """Release an iterator chain's background resources, walking
    ``.base`` links: threadbuffer producers (``close()``) and decode
    thread pools (``_pool``). The teardown for ANY chain — wrappers
    need not each forward close() for an abandoned chain to avoid
    leaking a spinning producer or an 8-thread executor."""
    seen = set()
    while it is not None and id(it) not in seen:
        seen.add(id(it))
        close = getattr(it, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                pass
        pool = getattr(it, "_pool", None)
        if pool is not None and hasattr(pool, "shutdown"):
            pool.shutdown(wait=False)
        it = getattr(it, "base", None)


def dist_slice(n: int, nworker: int, rank: int) -> slice:
    """Contiguous row range of worker ``rank`` of ``nworker`` over
    ``n`` rows — the imgrec byte-range rule applied to row-indexed
    sources (first ``n % nworker`` workers carry one extra row), so
    the union over ranks is exactly the full dataset."""
    if not 0 <= rank < nworker:
        raise ValueError(f"dist_worker_rank {rank} outside "
                         f"[0, dist_num_worker={nworker})")
    base, extra = divmod(n, nworker)
    start = rank * base + min(rank, extra)
    return slice(start, start + base + (1 if rank < extra else 0))


ITER_REGISTRY: Dict[str, Type[DataIter]] = {}


def register_iter(*names: str):
    def deco(cls):
        for n in names:
            ITER_REGISTRY[n] = cls
        return cls
    return deco


class SkipReadIterator(DataIter):
    """``test_skipread = 1`` (reference iter_batch_proc-inl.hpp:21,47,69):
    serve a cached batch without touching the source — the IO-benchmark
    knob that isolates read/decode cost from everything downstream.
    Bounded deviation from the reference (whose Next() returns the first
    batch FOREVER): the first epoch streams (and counts) real batches;
    every later epoch re-serves the first batch that many times. With
    ``test_io = 1`` over 2+ rounds the driver prints the real-IO rate
    (round 0) and the skipread rate (round 1+); the gap is the read/
    decode cost."""

    def __init__(self, base: DataIter):
        self.base = base
        self._first: Optional[DataBatch] = None
        self._count = 0
        self._known = False
        self._pos = 0
        super().__init__([])

    def before_first(self):
        self._pos = 0
        if not self._known:
            # an interrupted first pass must not leave a partial count
            # behind — only a COMPLETE first epoch defines the cadence
            self._count = 0
            self._first = None
            self.base.before_first()

    def next(self):
        if not self._known:
            b = self.base.next()
            if b is None:
                self._known = True
                # end-of-epoch stays None until before_first re-arms
                # (chained-iterator protocol: MNIST/CSV behave the same)
                self._pos = self._count
                return None
            if self._first is None:
                self._first = b
            self._count += 1
            return b
        if self._first is None or self._pos >= self._count:
            return None
        self._pos += 1
        return self._first


def dist_shardable_sources() -> list:
    """Source iterator types declaring ``supports_dist_shard``."""
    from . import proc, iter_imgrec, iter_img  # noqa: F401  (populate registry)
    return sorted(n for n, c in ITER_REGISTRY.items()
                  if c.supports_dist_shard)


def create_iterator(cfg: ConfigPairs) -> DataIter:
    """Build an iterator chain from one config section (reference
    data.cpp:27-94): each ``iter = <type>`` entry creates an iterator wrapping
    the previous one; every other pair is passed to all iterators in the
    chain (each ignores settings it does not understand)."""
    from . import proc, iter_imgrec, iter_img  # noqa: F401  (populate registry)
    kinds = [v for k, v in cfg if k == "iter"]
    params = [(k, v) for k, v in cfg if k != "iter"]
    it: Optional[DataIter] = None
    for kind in kinds:
        if kind == "end":
            continue
        if kind not in ITER_REGISTRY:
            raise ValueError(f"unknown iterator type {kind!r}")
        cls = ITER_REGISTRY[kind]
        if it is None:
            it = cls(params)
        else:
            it = cls(params, base=it)   # decorator iterators take base
        # init inner-to-outer so decorators always wrap a ready base
        it.init()
    if it is None:
        raise ValueError("config section declares no iterator")
    if any(k == "test_skipread" and str(v).strip() == "1"
           for k, v in params):
        it = SkipReadIterator(it)
    return it
