from .data import DataBatch, DataIter, create_iterator, register_iter
from . import proc  # noqa: F401  (register built-in iterators)

__all__ = ["DataBatch", "DataIter", "create_iterator", "register_iter"]
