"""RecordIO file format: framed, seekable, shardable record storage.

Reference analogs: dmlc-core RecordIO (used by the reference's modern
``imgrec`` path, /root/reference/src/io/iter_image_recordio-inl.hpp) and the
image record header (/root/reference/src/io/image_recordio.h:13-71: flag,
float label, 128-bit id, jpeg payload). The wire format here is our own —
cleaner 8-byte alignment, crc-free (fs-level integrity assumed), with the
same capabilities: magic-framed records that can be re-synced mid-file,
sharded readers by (part, nsplit) byte ranges, and an image record layout
carrying label vector + raw payload.

Layout per record:
    uint32 magic 0xCED7ABEF | uint32 payload_len | payload | pad to 8 bytes

Image payload:
    uint32 flag | uint64 id | uint32 nlabel | float32*nlabel | bytes image
"""

from __future__ import annotations

import dataclasses
import struct
from typing import BinaryIO, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..resilience import counters, failpoints
from .stream import getsize, sopen

MAGIC = 0xCED7ABEF
_HDR = struct.Struct("<II")
_IMG_HDR = struct.Struct("<IQI")


def _pad8(n: int) -> int:
    return (8 - n % 8) % 8


class RecordWriter:
    def __init__(self, path: str):
        self._f: BinaryIO = sopen(path, "wb")
        self.offsets: List[int] = []     # record start offsets, in order
        self._pos = 0

    def write(self, payload: bytes) -> None:
        self.offsets.append(self._pos)
        self._f.write(_HDR.pack(MAGIC, len(payload)))
        self._f.write(payload)
        self._f.write(b"\x00" * _pad8(len(payload)))
        self._pos += _HDR.size + len(payload) + _pad8(len(payload))

    def write_index(self, path: Optional[str] = None) -> str:
        """Write the record-offset index (default ``<rec>.idx``, one
        decimal offset per line — the analog of dmlc recordio's .idx).
        ``shard_record_counts`` uses it to answer distributed epoch-length
        checks without scanning the data file."""
        path = path or getattr(self._f, "name", None)
        if path is None:
            raise ValueError("write_index: pass a path (stream is unnamed)")
        idx_path = path if path.endswith(".idx") else path + ".idx"
        with sopen(idx_path, "wb") as f:
            f.write("\n".join(str(o) for o in self.offsets).encode()
                    + b"\n")
        return idx_path

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordReader:
    """Sequential reader over a byte range of a record file.

    ``part``/``nsplit`` shard the file by byte offset with re-sync to the
    next magic marker — the same distributed sharding contract as
    dmlc::InputSplit used at iter_image_recordio-inl.hpp:168-186 (each
    worker reads [part*size/n, (part+1)*size/n) resynced to record
    boundaries).

    Corruption handling: a record whose frame is damaged (bad magic —
    a flipped byte, a torn rewrite) is SKIPPED via the same ``_resync``
    machinery the shard boundaries use, counted on ``self.skipped`` and
    the process-wide ``recordio.skipped`` counter, and bounded by
    ``max_skip`` — past the bound the file is declared rotten and the
    read raises (one bad sector is survivable; a file that is mostly
    bad sectors is a data bug someone must see). A truncated FINAL
    record (killed packer) ends the shard silently, exactly like a
    shard boundary.
    """

    def __init__(self, path: str, part: int = 0, nsplit: int = 1,
                 max_skip: int = 100):
        self.path = path
        size = getsize(path)
        self._f = sopen(path, "rb")
        self.begin = size * part // nsplit
        self.end = size * (part + 1) // nsplit
        self.max_skip = int(max_skip)
        self.skipped = 0
        self._resync(self.begin)

    def _resync(self, pos: int) -> None:
        """Seek to ``pos`` then scan forward to the next record magic.
        If no magic exists in [pos, end) the reader lands on ``end`` and
        yields nothing (a shard can legally be empty). The scan starts at
        the next 8-aligned offset at-or-after ``pos`` — records start
        8-aligned, and rounding down would re-read a record owned by the
        previous shard (every shard reads the record spanning its end)."""
        pos = pos + (-pos) % 8
        want = struct.pack("<I", MAGIC)
        chunk_size = 1 << 16
        while pos < self.end:
            self._f.seek(pos)
            chunk = self._f.read(chunk_size)
            off = 0
            while True:
                idx = chunk.find(want, off)
                if idx < 0:
                    break
                if (pos + idx) % 8 == 0:
                    self._f.seek(pos + idx)
                    return
                off = idx + 1
            if len(chunk) < chunk_size:
                break                    # hit EOF without finding a record
            # overlap 7 bytes in case magic straddles the chunk boundary
            pos += len(chunk) - 7
        self._f.seek(self.end)

    def _skip_corrupt(self, at: int, why: str, resync: bool = True
                      ) -> None:
        """Account one corrupt record (and by default resync past it);
        raise once the bound is exhausted (an unbounded skip would
        happily 'read' a file of zeros as an empty dataset).
        ``resync=False`` when the file position already sits at the
        next record (decode-level faults with an intact frame)."""
        self.skipped += 1
        counters.inc("recordio.skipped")
        if self.skipped > self.max_skip:
            raise IOError(
                f"{self.path}: {self.skipped} corrupt records exceed "
                f"max_skip={self.max_skip} (last at byte {at}: {why}); "
                "repack the file")
        if resync:
            # the damaged frame starts at an 8-aligned offset; resume
            # the magic scan at the NEXT aligned slot to skip it
            self._resync(at + 8)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            at = self._f.tell()
            if at >= self.end:
                return
            hdr = self._f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return
            magic, ln = _HDR.unpack(hdr)
            if magic != MAGIC:
                self._skip_corrupt(at, "bad record magic")
                continue
            payload = self._f.read(ln)
            if len(payload) < ln:
                # short read: a genuinely torn TAIL (killed packer) ends
                # the shard silently — but a corrupted length field
                # mid-file reads to EOF the same way and must not drop
                # the rest of the shard uncounted. Resync decides:
                # another record past this point proves mid-file
                # corruption.
                self._resync(at + 8)
                if self._f.tell() >= self.end:
                    return           # torn tail: nothing real follows
                self._skip_corrupt(at, "bad record length",
                                   resync=False)
                continue
            self._f.read(_pad8(ln))
            if failpoints.fire("record.decode"):
                # injected decode fault: frame intact, payload declared
                # rotten; the position already sits at the next record
                self._skip_corrupt(at, "injected decode fault",
                                   resync=False)
                continue
            yield payload

    def reset(self) -> None:
        self._resync(self.begin)
        self.skipped = 0

    def close(self) -> None:
        self._f.close()


def shard_record_counts(path: str, nsplit: int) -> List[int]:
    """Per-shard record counts for the (part, nsplit) byte-range sharding of
    ``RecordReader`` in one sequential pass. A record belongs to the shard
    whose [begin, end) byte range contains its (8-aligned) start offset —
    the same membership rule the reader's resync/stop conditions implement.

    A ``<rec>.idx`` offset index (written by tools/im2rec.py /
    RecordWriter.write_index) answers this from the tiny index file alone.
    Without one, headers are parsed out of large buffered chunks —
    ~size/1MB sequential reads, which for a big multi-rank remote dataset
    means every rank streams the file once at init; pack with im2rec (or
    call write_index) to avoid that.

    """
    size = getsize(path)
    bounds = [size * k // nsplit for k in range(1, nsplit + 1)]
    counts = [0] * nsplit
    offsets = None
    try:
        with sopen(path + ".idx", "rb") as f:
            offsets = [int(line) for line in f.read().split() if line]
    except (OSError, ValueError):
        pass
    # trust the sidecar only when it provably describes THIS file: stale
    # or truncated indexes (rec rewritten without the idx, interrupted
    # pack) must fall through to the scan, or the round_batch deadlock
    # check they feed would silently pass on wrong counts
    if offsets and offsets == sorted(offsets) \
            and all(0 <= o < size for o in offsets):
        try:
            with sopen(path, "rb") as f:
                f.seek(offsets[0])
                magic0, _ = _HDR.unpack(f.read(_HDR.size))
                f.seek(offsets[-1])
                magic1, ln = _HDR.unpack(f.read(_HDR.size))
            last_end = offsets[-1] + _HDR.size + ln + _pad8(ln)
            if (magic0 == MAGIC and magic1 == MAGIC and offsets[0] == 0
                    and last_end == size):
                part = 0
                for o in offsets:
                    while o >= bounds[part]:
                        part += 1
                    counts[part] += 1
                return counts
        except (OSError, struct.error):
            pass
    chunk_size = 1 << 20
    with sopen(path, "rb") as f:
        pos, part = 0, 0
        buf, buf_start = b"", 0
        while True:
            off = pos - buf_start
            if off < 0 or off + _HDR.size > len(buf):
                f.seek(pos)
                buf = f.read(chunk_size)
                buf_start = pos
                off = 0
                if len(buf) < _HDR.size:
                    break
            magic, ln = _HDR.unpack_from(buf, off)
            if magic != MAGIC:
                raise IOError(f"{path}: bad record magic at {pos}")
            while pos >= bounds[part]:
                part += 1
            counts[part] += 1
            pos += _HDR.size + ln + _pad8(ln)
    return counts


@dataclasses.dataclass
class ImageRecord:
    """One packed image instance (reference image_recordio.h:13-71)."""
    inst_id: int
    labels: np.ndarray           # (nlabel,) float32
    data: bytes                  # encoded (jpeg/png) or raw payload
    flag: int = 0

    def pack(self) -> bytes:
        lab = np.asarray(self.labels, np.float32).ravel()
        return (_IMG_HDR.pack(self.flag, self.inst_id, lab.size)
                + lab.tobytes() + self.data)

    @classmethod
    def unpack(cls, payload: bytes) -> "ImageRecord":
        flag, inst_id, nlab = _IMG_HDR.unpack_from(payload, 0)
        off = _IMG_HDR.size
        labels = np.frombuffer(payload, np.float32, nlab, off).copy()
        return cls(inst_id=inst_id, labels=labels,
                   data=payload[off + 4 * nlab:], flag=flag)


def read_image_list(path: str) -> List[Tuple[int, np.ndarray, str]]:
    """Parse a ``.lst`` image list: tab/space separated
    ``index  label[ label2 ...]  relative_path`` (reference ImageLabelMap,
    iter_image_recordio-inl.hpp:28-90 and tools/im2rec.cc)."""
    out = []
    import io as _io
    with _io.TextIOWrapper(sopen(path, "rb")) as f:
        for line in f:
            parts = line.strip().split()
            if len(parts) < 3:
                continue
            idx = int(float(parts[0]))
            labels = np.asarray([float(x) for x in parts[1:-1]], np.float32)
            out.append((idx, labels, parts[-1]))
    return out
