"""Post-training int8 quantization (PTQ) of a verified checkpoint.

Per-channel symmetric weight quantization over the quantizable layer
types (fullc / conv / seqfc — everything whose ``wmat`` keeps its
output channels on the last axis), with activation scales calibrated
from a small batch stream (abs-max, optionally percentile-clipped).

The quantized layer's params carry everything the int8 execution path
(ops/fused_quant.py) needs, INSIDE the ordinary params tree:

    {"wmat":       int8, same shape as the source weight,
     "wmat_scale": f32 per-out-channel vector,
     "act_scale":  f32 scalar (calibrated activation clip),
     "bias":       untouched f32}

Because scales are plain leaves under ``params/<layer>/...`` they flow
through every existing surface unchanged: checkpoint digests cover
them, ``trainer._place`` replicates them (missing pspec keys fall back
to replicated), the engine's compiled closures take them as jit
arguments (hot reload stays zero-recompile), and layers detect the
quantized form by the presence of ``wmat_scale``.

The derived checkpoint round carries ``__quant_meta__`` in its meta
JSON (checkpoint.quant_meta): source round + blob_digest, calibration
config, and per-leaf drift metrics — the provenance chain the deploy
reject-list and tools/ckpt_health.py key on.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import checkpoint as ckpt
from ..config import QuantConfig
from ..telemetry.ledger import LEDGER

#: layer types eligible for weight quantization: their ``wmat`` stores
#: output channels on the LAST axis (fullc (in,out), conv HWIO, seqfc
#: (e,k)), which is what per-channel symmetric scaling assumes.
#: embed/posembed/mha/norm/moe stay fp32 — their weights either feed
#: gathers (no matmul to quantize) or carry params int8 would distort.
QUANT_LAYER_TYPES = ("fullc", "conv", "seqfc")

_TINY = 1e-12


def quantizable_layers(net) -> "Dict[str, str]":
    """Map quantizable layer name -> its input node name (the node whose
    captured activations calibrate ``act_scale``). Shared (weight-tied)
    layers reuse the primary's params entry, so each name appears once."""
    g = net.graph
    out: Dict[str, str] = {}
    for spec in g.layers:
        if spec.type in QUANT_LAYER_TYPES and not spec.is_shared \
                and spec.name not in out:
            out[spec.name] = g.node_names[spec.nindex_in[0]]
    return out


def _rms(a: np.ndarray) -> float:
    return float(np.sqrt(np.mean(np.square(a, dtype=np.float64))))


def quantize_weight(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-out-channel symmetric int8: scale[c] = absmax(|w[..., c]|)/127
    (all-zero channels get scale 1 so dequant stays exact)."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def weight_drift(w: np.ndarray, q: np.ndarray,
                 scale: np.ndarray) -> Dict[str, float]:
    """Round-trip drift of one quantized leaf: relative RMS error of
    dequant(q) vs the source weight, and the saturation fraction
    (|q| == 127 — a high fraction means the per-channel range clipped
    real mass, the classic sign of an outlier channel)."""
    w = np.asarray(w, np.float32)
    deq = q.astype(np.float32) * scale
    return {
        "rel_err": _rms(deq - w) / max(_rms(w), _TINY),
        "sat_frac": float(np.mean(np.abs(q.astype(np.int32)) == 127)),
    }


def calibrate_act_scales(net, params, state, batches: Iterable[Any],
                         percentile: float = 100.0) -> Dict[str, float]:
    """Run the source (fp) model over the calibration stream with node
    capture on and record, per quantizable layer, the max over batches
    of the |input| abs-max (percentile < 100 clips each batch's tail
    first — rare outliers trade for int8 resolution). Batches are NHWC
    arrays as the engine feeds them."""
    targets = quantizable_layers(net)
    scales: Dict[str, float] = {}
    n_batches = 0
    for batch in batches:
        res = net.apply(params, state, batch, train=False,
                        capture_nodes=True)
        n_batches += 1
        for lname, node in targets.items():
            v = res.nodes.get(node)
            if v is None:
                continue
            v = np.abs(np.asarray(v, np.float32))
            s = float(np.max(v)) if percentile >= 100.0 \
                else float(np.percentile(v, percentile))
            scales[lname] = max(scales.get(lname, 0.0), s)
    if not n_batches:
        raise ValueError("quantize: calibration stream yielded no batches")
    # a layer whose input never fired (or is all-zero) calibrates to 1.0
    # rather than 0 (a zero act_scale would divide out the whole input)
    return {ln: (scales.get(ln) or 1.0) for ln in targets}


def quantize_params(params: Dict[str, Any],
                    act_scales: Dict[str, float]
                    ) -> Tuple[Dict[str, Any], Dict[str, Dict[str, float]]]:
    """Produce the quantized params tree (source tree untouched) plus
    per-layer drift metrics. Only layers named in ``act_scales`` with a
    ``wmat`` leaf quantize; everything else passes through by
    reference."""
    out: Dict[str, Any] = {}
    drift: Dict[str, Dict[str, float]] = {}
    for lname, lp in params.items():
        if lname in act_scales and isinstance(lp, dict) and "wmat" in lp:
            w = np.asarray(lp["wmat"])
            q, scale = quantize_weight(w)
            qp = dict(lp)
            qp["wmat"] = q
            qp["wmat_scale"] = scale
            qp["act_scale"] = np.float32(act_scales[lname])
            out[lname] = qp
            drift[lname] = weight_drift(w, q, scale)
        else:
            out[lname] = lp
    return out, drift


def dequantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Fold the scales back in: int8 wmat -> f32 wmat, scale leaves
    dropped. Structure-compatible with the source checkpoint (used by
    fp engines negotiating a quantized blob, and by the deploy gate's
    quantized-vs-incumbent comparison)."""
    out: Dict[str, Any] = {}
    for lname, lp in params.items():
        if isinstance(lp, dict) and "wmat_scale" in lp:
            qp = dict(lp)
            scale = np.asarray(qp.pop("wmat_scale"), np.float32)
            qp.pop("act_scale", None)
            qp["wmat"] = np.asarray(qp["wmat"], np.float32) * scale
            out[lname] = qp
        else:
            out[lname] = lp
    return out


def is_quantized_params(params: Dict[str, Any]) -> bool:
    """Whether any layer in the tree carries the int8 form."""
    return any(isinstance(lp, dict) and "wmat_scale" in lp
               for lp in params.values())


def dequantize_blob(blob: Dict[str, Any]) -> Dict[str, Any]:
    """Blob-level :func:`dequantize_params` (meta/state pass through;
    the meta keeps ``__quant_meta__`` so provenance survives)."""
    out = dict(blob)
    out["params"] = dequantize_params(blob["params"])
    return out


def drift_verdict(qm: Dict[str, Any], max_rel_err: float,
                  max_sat_frac: float) -> Dict[str, Any]:
    """Quantized-vs-source verdict over the drift metrics stored in a
    ``__quant_meta__`` block: SAFE when every quantized leaf's relative
    RMS error and saturation fraction clear the thresholds. Shared by
    tools/ckpt_health.py (human report) and deploy's offline gate (a
    drift-unsafe quantized round never reaches a canary)."""
    rows: List[Dict[str, Any]] = []
    worst_err = worst_sat = 0.0
    offenders = []
    for lname in sorted(qm.get("drift", {})):
        d = qm["drift"][lname]
        ok = (d["rel_err"] <= max_rel_err
              and d["sat_frac"] <= max_sat_frac)
        if not ok:
            offenders.append(lname)
        worst_err = max(worst_err, d["rel_err"])
        worst_sat = max(worst_sat, d["sat_frac"])
        rows.append({"layer": lname, "rel_err": d["rel_err"],
                     "sat_frac": d["sat_frac"], "ok": ok})
    ok = not offenders and bool(rows)
    verdict = "SAFE" if ok else "UNSAFE"
    line = (f"quant drift {verdict}: {len(rows)} quantized layers, "
            f"worst rel_err {worst_err:.4f} (max {max_rel_err}), "
            f"worst sat_frac {worst_sat:.4f} (max {max_sat_frac})"
            + (f"; offenders: {', '.join(offenders)}" if offenders
               else ""))
    return {"ok": ok, "verdict": verdict, "line": line, "layers": rows,
            "worst_rel_err": worst_err, "worst_sat_frac": worst_sat,
            "source_round": qm.get("source_round"),
            "source_digest": qm.get("source_digest")}


def quantize_blob(net, blob: Dict[str, Any], batches: Iterable[Any],
                  qc: QuantConfig) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Full PTQ pass over a loaded inference blob: calibrate activation
    scales on the fp model, quantize the weights, and assemble the
    ``__quant_meta__`` provenance block. Returns ``(qblob, quant_meta)``
    — the caller decides the output round (write_quantized_round).
    Emits the ``quant_calibrate`` ledger event."""
    t0 = time.perf_counter()
    src_digest = ckpt.blob_digest(blob["meta"])
    act_scales = calibrate_act_scales(
        net, blob["params"], blob["state"], batches,
        percentile=qc.calib_percentile)
    qparams, drift = quantize_params(blob["params"], act_scales)
    if not drift:
        raise ValueError(
            "quantize: model has no quantizable layers "
            f"(looked for {', '.join(QUANT_LAYER_TYPES)})")
    qm = {
        "quant_dtype": "int8",
        "source_round": int(blob["meta"]["round"]),
        "source_digest": src_digest,
        "calib": {"batches": int(qc.calib_batches),
                  "percentile": float(qc.calib_percentile)},
        "act_scales": {k: float(v) for k, v in act_scales.items()},
        "quantized_layers": sorted(drift),
        "drift": {k: {"rel_err": float(v["rel_err"]),
                      "sat_frac": float(v["sat_frac"])}
                  for k, v in drift.items()},
    }
    qblob = dict(blob)
    qblob["params"] = qparams
    LEDGER.event("quant_calibrate",
                 source_round=qm["source_round"],
                 source_digest=src_digest,
                 layers=len(drift),
                 percentile=float(qc.calib_percentile),
                 seconds=round(time.perf_counter() - t0, 4))
    return qblob, qm


def write_quantized_round(path: str, structure_sig: tuple,
                          qblob: Dict[str, Any],
                          qm: Dict[str, Any]) -> None:
    """Persist the derived round: same structure signature as the
    source (quantization changes leaves, not the DAG), source round's
    epoch/step carried through, ``__quant_meta__`` riding the meta
    JSON. The archive gets its own digests, so ``blob_digest`` of the
    quantized round is a distinct content identity."""
    meta = qblob["meta"]
    ckpt.save_model(
        path, structure_sig=structure_sig,
        round_counter=int(meta["round"]),
        epoch_counter=int(meta["epoch"]),
        params=qblob["params"], net_state=qblob["state"],
        opt_state=None,
        step_count=int(meta.get("step_count", 0)),
        lr_scale=float(meta.get("lr_scale", 1.0)),
        extra_meta={"__quant_meta__": qm})
