"""Post-training quantization (doc/tasks.md "Quantized serving &
cascade"): PTQ pass over verified checkpoints, drift verdicts, and the
dequantize helpers the serve/deploy planes negotiate with."""

from .ptq import (QUANT_LAYER_TYPES, calibrate_act_scales,
                  dequantize_blob, dequantize_params, drift_verdict,
                  is_quantized_params, quantizable_layers, quantize_blob,
                  quantize_params, quantize_weight, weight_drift,
                  write_quantized_round)

__all__ = [
    "QUANT_LAYER_TYPES", "calibrate_act_scales", "dequantize_blob",
    "dequantize_params", "drift_verdict", "is_quantized_params",
    "quantizable_layers", "quantize_blob", "quantize_params",
    "quantize_weight", "weight_drift", "write_quantized_round",
]
