"""Network: compile a NetGraph into a pure, jittable forward function.

TPU-native replacement for the reference's NeuralNet<xpu> DAG executor
(/root/reference/src/nnet/neural_net-inl.hpp:23-318). The reference allocates
per-device Node buffers, runs layer->Forward over connections in order, and
hand-written layer->Backprop in reverse (activations doubling as gradient
storage). Here the whole graph is one pure function of (params, state, batch):
node values are a functional list, losses are summed into a scalar, and
``jax.grad`` of that scalar reproduces every hand-written backward pass.
Shared layers (kSharedLayer weight tying, neural_net-inl.hpp:259-265) reuse
the primary layer's parameter subtree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .config import ConfigPairs, Policy
from .graph import NetGraph, global_param, policy_from_config
from .layers import ApplyCtx, Layer, create_layer
from .layers.base import Shape3, is_flat, to_nhwc

Params = Dict[str, Dict[str, jax.Array]]
NetState = Dict[str, Any]


@dataclasses.dataclass
class ForwardResult:
    loss: jax.Array                       # scalar total loss
    state: NetState                       # updated layer state (BN stats, ...)
    nodes: Optional[Dict[str, jax.Array]]  # node name -> value (if captured)
    out: jax.Array                        # value of the last node (predictions)
    # per-layer activation health stats (model-health probe; None unless
    # apply(health=True)): {layer: {"absmax", "zero_frac"?, "bn_var_min"?}}
    health: Optional[Dict[str, Any]] = None


class Network:
    """Static graph + layer objects; all runtime data flows through apply."""

    def __init__(self, graph: NetGraph, cfg: ConfigPairs):
        self.graph = graph
        if graph.input_shape is None:
            raise ValueError("input_shape must be set")
        # mixed-precision policy: fp32 master params/outputs, activations
        # and gradients in compute_dtype (config.Policy); per-layer casts
        # happen at apply time inside jit so XLA fuses them
        self.policy: Policy = policy_from_config(cfg)
        self.compute_dtype = self.policy.compute_dtype
        # remat = 1: rematerialize each layer's activations in the backward
        # pass (jax.checkpoint) — trades FLOPs for HBM, the standard TPU
        # recipe for memory-bound models (no reference analog; the closest
        # is temp_col_max's memory/compute staging, SURVEY §5)
        self.remat = bool(int(global_param(cfg, "remat", "0")))
        # fused Pallas kernel suite (ops/fused.py; doc/tasks.md "Fused
        # kernels"): fused_kernels = auto|1|0 — auto selects on TPU,
        # 1 forces (interpret off-TPU, the test path), 0 restores the
        # jnp references. The trainer clears fused_single_device on
        # multi-device meshes: a pallas_call is opaque to the GSPMD
        # partitioner and fused BN moments would be shard-local where
        # the jnp path is sync-BN.
        from .ops.fused import resolve_mode
        self.fused_mode = resolve_mode(
            global_param(cfg, "fused_kernels", "auto"))
        self.fused_single_device = True
        # mesh context (ops.fused.FusedSpmd) the trainer binds on
        # multi-device meshes: fused ops then run as shard_map islands
        # with per-op collectives instead of being cleared wholesale
        self.fused_spmd = None
        self._tp_plan_logged = False
        # rule-driven sharding (parallel/rules.py): the validated
        # config namespace (partition_rules / fsdp_*), custom rules
        # prepended to the generated per-model table
        from .graph import sharding_from_config
        self.sharding_cfg = sharding_from_config(cfg)
        self._rule_pspecs_cache = None
        self._param_shapes_cache = None
        # build layer objects; shared specs reuse the primary object
        self.layers: List[Layer] = []
        for spec in graph.layers:
            if spec.is_shared:
                self.layers.append(self.layers[spec.primary_layer_index])
            else:
                self.layers.append(create_layer(spec, graph.defcfg))
        # shape inference over the DAG (reference InitNet/InitConnection)
        self.node_shapes: List[Optional[Shape3]] = [None] * graph.num_nodes
        self.node_shapes[0] = graph.input_shape
        for i in range(graph.extra_data_num):
            self.node_shapes[1 + i] = graph.extra_shapes[i]
        self.layer_out_shapes: List[List[Shape3]] = []
        for li, (spec, layer) in enumerate(zip(graph.layers, self.layers)):
            in_shapes = []
            for ni in spec.nindex_in:
                if self.node_shapes[ni] is None:
                    raise ValueError(
                        f"layer {spec.name!r}: input node "
                        f"{graph.node_names[ni]!r} has no value yet")
                in_shapes.append(self.node_shapes[ni])
            out_shapes = layer.infer_shapes(in_shapes)
            self.layer_out_shapes.append(out_shapes)
            for ni, s in zip(spec.nindex_out, out_shapes):
                self.node_shapes[ni] = s
        self.loss_layers = [(li, l) for li, l in enumerate(self.layers)
                            if l.is_loss]
        self._in_shapes_of = [
            [self.node_shapes[ni] for ni in spec.nindex_in]
            for spec in graph.layers]
        # static activation-fold plan (graph.act_fusion_plan): producer
        # layers absorb a following relu into their (possibly fused)
        # epilogue; the folded relus pass through in apply(). Numerics
        # are backend-independent — producers apply the act on their
        # reference path too — so the plan is computed unconditionally
        # unless the knob is a hard off.
        if self.fused_mode != "off":
            from .graph import act_fusion_plan
            self._fuse_act, self._act_folded = act_fusion_plan(graph)
        else:
            self._fuse_act, self._act_folded = {}, set()
        # stem channel padding (graph.stem_pad_plan): value-exact, so on
        # by default; stem_pad = 0 disables, stem_pad = N (>= 2)
        # overrides the pad-to width (default 4 — lane/sublane-friendly
        # for the RGB stem and its space-to-depth fold). "1"/"on" mean
        # ON at the default width, matching the sibling knobs'
        # (fused_kernels, input_fold) auto|1|0 grammar — a width of 1
        # could never pad anything and silently-off would invert the
        # user's intent.
        sp = global_param(cfg, "stem_pad", "auto").strip().lower()
        if sp in ("0", "off", "false", "no"):
            self._cin_pad = {}
        else:
            from .graph import stem_pad_plan
            pad_to = int(sp) if sp.isdigit() and int(sp) >= 2 else 4
            self._cin_pad = stem_pad_plan(graph, pad_to=pad_to)

    def _fused_now(self) -> bool:
        """Per-trace fused-kernel decision: knob/env x backend (ops.
        fused.kernels_active) x the trainer's mesh gate — which now
        either binds a ``fused_spmd`` island context (dp meshes) or
        clears ``fused_single_device`` (topologies the islands do not
        cover), never both."""
        from .ops.fused import kernels_active
        return ((self.fused_single_device or self.fused_spmd is not None)
                and kernels_active(self.fused_mode))

    # -- init --------------------------------------------------------------
    def init(self, key: jax.Array) -> Tuple[Params, NetState]:
        """Initialize params + state (reference NeuralNet::InitModel,
        neural_net-inl.hpp:68-86; per-layer RNG keys replace the per-device
        seeded mshadow::Random)."""
        params: Params = {}
        state: NetState = {}
        for li, (spec, layer) in enumerate(zip(self.graph.layers, self.layers)):
            if spec.is_shared:
                continue
            in_shapes = self._in_shapes_of[li]
            if layer.has_params:
                params[layer.name] = layer.init_params(
                    jax.random.fold_in(key, li), in_shapes)
            st = layer.init_state(in_shapes)
            if st:
                state[layer.name] = st
        return params, state

    # -- forward -----------------------------------------------------------
    def apply(self,
              params: Params,
              state: NetState,
              data: jax.Array,
              label: Optional[jax.Array] = None,
              mask: Optional[jax.Array] = None,
              extra_data: Tuple[jax.Array, ...] = (),
              rng: Optional[jax.Array] = None,
              train: bool = False,
              capture_nodes: bool = False,
              seq_axis: Optional[str] = None,
              data_axis: Optional[str] = None,
              label_slices: Optional[Dict[Tuple[int, int],
                                          jax.Array]] = None,
              compute_dtype: Optional[Any] = None,
              health: bool = False) -> ForwardResult:
        """One forward pass. ``data`` is NHWC (batch, y, x, c) or flat
        (batch,1,1,n); ``label`` is (batch, label_width); ``mask`` is (batch,)
        marking real rows (None = all real). ``label_slices`` maps a loss
        layer's global label_vec range to its (pre-sliced) label array —
        used under sequence parallelism, where the full-width label cannot
        be sliced locally with global indices (each shard holds its own
        token-aligned columns of every slice). ``compute_dtype`` overrides
        the config policy's compute dtype for this call — the serve
        engine's per-engine ``dtype`` option (a checkpoint trained fp32
        can serve bf16 and vice versa; fp32 masters make the cast safe).
        ``health=True`` taps per-layer activation stats (abs-max,
        dead-ReLU zero fraction, BN batch-variance floor) into
        ``result.health`` through the ``ApplyCtx.health_sink`` hook —
        the model-health probe's in-trace activation view
        (telemetry/modelhealth.py); False adds zero ops."""
        g = self.graph
        batch = data.shape[0]
        nodes: List[Optional[jax.Array]] = [None] * g.num_nodes
        nodes[0] = data
        for i, ed in enumerate(extra_data):
            nodes[1 + i] = ed
        if mask is None:
            mask = jnp.ones((batch,), jnp.float32)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        new_state: NetState = dict(state)
        cdt = self.compute_dtype if compute_dtype is None else compute_dtype
        fused_now = self._fused_now()
        health_sink: Optional[Dict[str, Any]] = {} if health else None
        total_loss = jnp.zeros((), jnp.float32)
        for li, (spec, layer) in enumerate(zip(g.layers, self.layers)):
            if li in self._act_folded:
                # relu folded into its producer's epilogue
                # (graph.act_fusion_plan): the producer already applied
                # it, so this layer is a pass-through — the tap still
                # fires (the node holds the post-activation value, so
                # the dead-ReLU fraction stays meaningful)
                nodes[spec.nindex_out[0]] = nodes[spec.nindex_in[0]]
                if health_sink is not None:
                    self._health_tap(health_sink, spec, nodes, train)
                continue
            ctx = ApplyCtx(train=train, rng=jax.random.fold_in(rng, li),
                           compute_dtype=cdt,
                           seq_axis=seq_axis, data_axis=data_axis,
                           fused=fused_now,
                           fused_spmd=self.fused_spmd if fused_now
                           else None,
                           fuse_act=self._fuse_act.get(li),
                           cin_pad=self._cin_pad.get(li),
                           health_sink=health_sink)
            inputs = [nodes[ni] for ni in spec.nindex_in]
            lparams = params.get(layer.name, {})
            lstate = new_state.get(layer.name, {})
            if self.remat and layer.has_params:
                def _fn(lp, ls, rng_, *ins, _layer=layer, _ctx=ctx):
                    c = ApplyCtx(train=_ctx.train, rng=rng_,
                                 compute_dtype=_ctx.compute_dtype,
                                 seq_axis=_ctx.seq_axis,
                                 data_axis=_ctx.data_axis,
                                 fused=_ctx.fused,
                                 fused_spmd=_ctx.fused_spmd,
                                 fuse_act=_ctx.fuse_act,
                                 cin_pad=_ctx.cin_pad)
                    return _layer.apply(lp, ls, list(ins), c)
                outputs, lstate_out = jax.checkpoint(_fn)(
                    lparams, lstate, ctx.rng, *inputs)
            else:
                outputs, lstate_out = layer.apply(lparams, lstate, inputs, ctx)
            if lstate_out:
                new_state[layer.name] = lstate_out
                # auxiliary regularizers (e.g. MoE load-balancing loss)
                # ride the state dict under "_aux_loss" and only count
                # during training
                if train and "_aux_loss" in lstate_out:
                    total_loss = total_loss + lstate_out["_aux_loss"]
            for ni, out in zip(spec.nindex_out, outputs):
                nodes[ni] = out
            if health_sink is not None:
                self._health_tap(health_sink, spec, nodes, train)
            if layer.is_loss and (label is not None
                                  or label_slices is not None):
                a, b = g.label_slice(layer.target)
                lab = (label_slices[(a, b)] if label_slices is not None
                       else label[:, a:b])
                total_loss = total_loss + layer.loss(
                    outputs, lab.astype(jnp.float32), mask)
        node_map = None
        if capture_nodes:
            node_map = {name: nodes[i] for i, name in enumerate(g.node_names)
                        if nodes[i] is not None}
        # "last node" = output of the final layer (reference ForwardTo default
        # req = top node, nnet_impl-inl.hpp:203-216)
        out = nodes[g.layers[-1].nindex_out[0]] if g.layers else data
        return ForwardResult(loss=total_loss, state=new_state,
                             nodes=node_map, out=out, health=health_sink)

    #: layer types whose exact-zero output fraction IS the dead-unit
    #: signal (a relu that emits 0 for every batch row is a dead unit;
    #: sustained growth of that fraction is the classic silent-failure
    #: mode the OPT run logs watched per layer)
    _HEALTH_DEAD_TYPES = frozenset({"relu"})

    def _health_tap(self, sink: Dict[str, Any], spec, nodes,
                    train: bool) -> None:
        """Per-layer activation stats for the model-health probe (all
        fp32 scalars, computed in-trace): abs-max for every layer,
        exact-zero output fraction for relus (dead-ReLU signal), and
        the minimum per-channel batch variance of a batch_norm layer's
        INPUT (train only — the quantity whose collapse toward 0 makes
        the BN rsqrt amplify noise). Padding rows are included in the
        batch statistics — a per-epoch tail effect too small to gate
        on. A plugin layer may have added its own entry via
        ``ctx.health_sink``; the standard taps win on a name clash."""
        out = nodes[spec.nindex_out[0]]
        x32 = out.astype(jnp.float32)
        ent: Dict[str, jax.Array] = {"absmax": jnp.max(jnp.abs(x32))}
        if spec.type in self._HEALTH_DEAD_TYPES:
            ent["zero_frac"] = jnp.mean((x32 == 0.0).astype(jnp.float32))
        if train and spec.type in ("batch_norm", "batch_norm_no_ma"):
            xin = nodes[spec.nindex_in[0]].astype(jnp.float32)
            axes = tuple(range(xin.ndim - 1))
            var = jnp.maximum(
                jnp.mean(jnp.square(xin), axes)
                - jnp.square(jnp.mean(xin, axes)), 0.0)
            ent["bn_var_min"] = jnp.min(var)
        sink[spec.name] = ent

    # -- pipeline staging (config-driven pp, parallel/pipeline.py) ---------
    def stage_partition(self, n_stages: int) -> List[Tuple[int, int]]:
        """Partition layers into ``n_stages`` contiguous [lo, hi) ranges
        from per-layer ``stage = k`` config annotations (a layer without
        one inherits the previous layer's stage). Loss layers are excluded
        from the pipeline body — they run on the reassembled full batch.
        Validates: stages non-decreasing and covering 0..S-1, no reads
        from later stages, and no stateful layers in the body beyond
        batch_norm/moe (whose moments/aux-loss ride the schedule's
        sinks). Cross-stage skips and heterogeneous boundary shapes are
        fine: each boundary's carried node set (``self._stage_carried``)
        flat-packs into one ring register (trainer pack/unpack)."""
        g = self.graph
        n_body = len(g.layers)
        while n_body and self.layers[n_body - 1].is_loss:
            n_body -= 1
        for li in range(n_body):
            if self.layers[li].is_loss:
                raise ValueError(
                    "pipeline_parallel: loss layers must come last")
        stages = []
        cur = 0
        for li in range(n_body):
            for k, v in g.layers[li].cfg:
                if k == "stage":
                    nxt = int(v)
                    if nxt < cur or nxt > cur + 1:
                        raise ValueError(
                            f"pipeline stage ids must be contiguous and "
                            f"non-decreasing; layer {g.layers[li].name!r} "
                            f"jumps {cur} -> {nxt}")
                    cur = nxt
            stages.append(cur)
        if cur != n_stages - 1:
            raise ValueError(
                f"config declares stages 0..{cur} but pipeline_parallel = "
                f"{n_stages}")
        ranges: List[Tuple[int, int]] = []
        lo = 0
        for s in range(n_stages):
            hi = lo
            while hi < n_body and stages[hi] == s:
                hi += 1
            if hi == lo:
                raise ValueError(f"pipeline stage {s} has no layers")
            ranges.append((lo, hi))
            lo = hi
        # validations over the partition
        node_stage = {0: 0}
        for i in range(g.extra_data_num):
            node_stage[1 + i] = 0
        last_consumer: Dict[int, int] = {}
        for s, (lo, hi) in enumerate(ranges):
            for li in range(lo, hi):
                layer, spec = self.layers[li], g.layers[li]
                if ((layer.has_state or layer.init_state(
                        self._in_shapes_of[li]))
                        and not getattr(layer, "pp_batch_stats", False)
                        and not getattr(layer, "pp_aux_loss", False)
                        and not getattr(layer, "pp_state_tick", False)):
                    # batch_norm is admitted: its microbatch moments ride
                    # the schedule's stat sink and merge after the ring.
                    # moe is admitted: its _aux_loss rides the schedule's
                    # per-stage scalar accumulator (differentiated).
                    # insanity is admitted: its annealing counter is read
                    # frozen by the microbatches and ticked once per step
                    # by the trainer (pp_state_tick). Remaining stateful
                    # layers (pairtest's divergence log) cannot pipeline.
                    raise ValueError(
                        f"pipeline_parallel: stateful layer "
                        f"{spec.name!r} ({spec.type}) is not supported in "
                        f"the pipeline body")
                for ni in spec.nindex_in:
                    src = node_stage.get(ni)
                    if src is None:
                        raise ValueError(
                            f"layer {spec.name!r}: input node produced in "
                            "a later stage")
                    # cross-stage reads are fine: every node produced in
                    # stages <= i and consumed after i rides the flat ring
                    # register (see stage_carried / _pp_pipeline_fn pack)
                    last_consumer[ni] = max(last_consumer.get(ni, -1), s)
                for ni in spec.nindex_out:
                    # FIRST production stage: an in-place (layer[+0])
                    # rewrite in a later stage must not hide the node from
                    # earlier boundaries — the pre-rewrite value still has
                    # to ride the register to reach that stage (pack reads
                    # the stage-local node map, so each boundary carries
                    # the latest value at its cut)
                    node_stage.setdefault(ni, s)
        # the loss tail runs on the reassembled batch, seeded with the top
        # body node PLUS any other body node a tail layer reads (auxiliary
        # loss heads, GoogLeNet-style): each extra seed rides the carried
        # register to the last stage like any cross-stage skip
        top_node = g.layers[n_body - 1].nindex_out[0]
        tail_avail = {top_node}
        tail_reads = set()
        for li in range(n_body, len(g.layers)):
            spec = g.layers[li]
            for ni in spec.nindex_in:
                if ni not in tail_avail:
                    if ni not in node_stage:
                        raise ValueError(
                            f"pipeline_parallel: loss-tail layer "
                            f"{spec.name!r} reads node "
                            f"{g.node_names[ni]!r}, which no pipeline "
                            "body stage produces")
                    tail_reads.add(ni)
                    tail_avail.add(ni)
            tail_avail.update(spec.nindex_out)
        self._tail_seeds = sorted({top_node} | tail_reads)
        # carried set per boundary i: nodes produced in stages <= i still
        # needed after i — every tail seed (the final body node, plus aux
        # loss-head inputs) is "consumed" by the loss tail, so it is
        # carried to the end. Boundary shapes/counts may differ per cut:
        # the trainer packs each boundary's carried nodes into one flat
        # max-size ring register (_pp_pipeline_fn pack).
        for ni in self._tail_seeds:
            last_consumer[ni] = len(ranges)
        self._stage_carried = [
            sorted(ni for ni, s_prod in node_stage.items()
                   if s_prod <= i and last_consumer.get(ni, -1) > i)
            for i in range(len(ranges) - 1)]
        for i, carried in enumerate(self._stage_carried):
            if not carried:
                raise ValueError(
                    f"pipeline boundary {i} carries no nodes — stage "
                    f"{i + 1} reads nothing from earlier stages")
        return ranges

    def tp_manual_plan(self, tp_size: int, stage_ranges=None,
                       train: bool = True) -> Dict[int, Dict[str, Any]]:
        """Static plan for MANUAL tensor parallelism inside pipeline stages.
        The pp step cannot leave the model axis to GSPMD — automatic
        partitioning inserts model-axis collectives *inside* lax.switch
        branches with module-wide rendezvous, which deadlocks (devices in
        different stages never reach each other's ops). The manual scheme
        slices each planned weight along its 'model' dim (zero-padded to a
        tp multiple when the dim does not divide) and computes with the
        local shard; the output all-gather is DEFERRED through chains of
        channel-wise followers (``Layer.tp_follow`` — BN, activations,
        pooling, bias/prelu, whose per-channel params/state slice along)
        and lands only where a channel-mixing consumer needs the full
        activation, or at the stage boundary. Every collective stays
        scoped to the model peers of one stage, which all execute the
        same branch — the generalization of the reference's fullc_gather
        hybrid (async_updater-inl.hpp:68-94).

        Returns {layer_index: entry} with optional entry keys:
          ``params``  {key: (dim, orig)} — pad dim to a tp multiple of
                      orig, then slice this shard's span;
          ``state``   {key: orig} — dim-0 channel slices (BN running
                      stats at eval);
          ``gather``  {input_pos: orig} — all-gather(+trim) these inputs
                      before apply (first channel-mixing consumer);
          ``out_sharded`` orig — outputs stay channel-sharded;
          ``sink_gather`` orig — all-gather this layer's stat-sink
                      moments back to full width after apply.
        ``stage_ranges`` must be the pipeline's body partition — sharded
        values never cross a stage boundary (apply_stage gathers wanted
        nodes at stage end), so the walk resets per stage."""
        plan: Dict[int, Dict[str, Any]] = {}
        if tp_size <= 1:
            return plan
        g = self.graph
        ranges = stage_ranges or [(0, len(g.layers))]
        excluded: List[Tuple[str, str]] = []
        followed: List[str] = []

        def slice_dims(li, layer):
            """{key: (dim, orig)} for a producer slice, or a reason str.
            Specs come from the RULE TABLE (param_pspecs), not the
            layer declaration directly — a config ``partition_rules``
            override changes the manual plan the same way it changes
            GSPMD placement, keeping the 0.4.x execution fallback
            derived from the one declarative source."""
            if getattr(layer, "tp_manual_axis", None) is None:
                return "no tp_manual_axis"
            pspecs = self.param_pspecs().get(layer.name) or {}
            shapes = self.param_shapes().get(layer.name, {})
            # rules cover only params the layer actually created
            # (no_bias conv has no "bias" leaf to match)
            dims = {key: d for key, ps in pspecs.items() if key in shapes
                    for d, ax in enumerate(ps)
                    if ax == "model"
                    or (isinstance(ax, tuple) and "model" in ax)}
            if not dims:
                return "no 'model' dim in the partition rules"
            sizes = {shapes[key].shape[d] for key, d in dims.items()}
            if len(sizes) != 1:
                return "mixed 'model' dims"
            orig = sizes.pop()
            if orig < tp_size:
                return f"'model' dim {orig} < tp {tp_size}"
            return {key: (d, orig) for key, d in dims.items()}

        for lo, hi in ranges:
            sharded: Dict[int, int] = {}   # node -> orig trailing width
            for li in range(lo, hi):
                spec, layer = g.layers[li], self.layers[li]
                ent: Dict[str, Any] = {}
                in_sh = {pos: sharded[ni]
                         for pos, ni in enumerate(spec.nindex_in)
                         if ni in sharded}
                if in_sh:
                    can_follow = (len(spec.nindex_in) == 1
                                  and len(spec.nindex_out) == 1
                                  and not spec.is_shared
                                  and layer.tp_followable(train))
                    if can_follow:
                        orig = in_sh[0]
                        if layer.tp_channel_params:
                            ent["params"] = {k: (0, orig)
                                             for k in layer.tp_channel_params}
                        if layer.tp_channel_state and layer.has_state:
                            ent["state"] = {k: orig
                                            for k in layer.tp_channel_state}
                        if getattr(layer, "pp_batch_stats", False):
                            ent["sink_gather"] = orig
                        ent["out_sharded"] = orig
                        sharded[spec.nindex_out[0]] = orig
                        followed.append(layer.name)
                        plan[li] = ent
                        continue
                    ent["gather"] = dict(in_sh)
                    for pos in in_sh:
                        sharded.pop(spec.nindex_in[pos], None)
                if not spec.is_shared and layer.has_params:
                    dims = slice_dims(li, layer)
                    if isinstance(dims, str):
                        excluded.append((layer.name, dims))
                    else:
                        orig = next(iter(dims.values()))[1]
                        ent["params"] = dims
                        ent["out_sharded"] = orig
                        sharded[spec.nindex_out[0]] = orig
                if ent:
                    plan[li] = ent
        # layers outside the plan compute replicated — say so once, loudly
        # enough to explain a flat memory/throughput curve, quiet enough
        # not to spam (grouped by reason, a few example names each)
        if not self._tp_plan_logged:
            self._tp_plan_logged = True
            by_reason: Dict[str, List[str]] = {}
            for n, why in excluded:
                by_reason.setdefault(why, []).append(n)
            detail = "; ".join(
                f"{why}: {len(names)} ({', '.join(names[:4])}"
                + (", ..." if len(names) > 4 else "") + ")"
                for why, names in by_reason.items())
            print(f"tp_manual_plan: {len(excluded)}/{len(self.layers)} "
                  f"layer(s) compute replicated across the model axis "
                  f"(tp={tp_size}); {len(followed)} follow channel-sharded"
                  + (f" — {detail}" if detail else ""))
        return plan

    def apply_stage(self, lo: int, hi: int, params: Params, seed,
                    rng: jax.Array, train: bool,
                    state: Optional[NetState] = None,
                    tp_axis: Optional[str] = None,
                    tp_size: int = 1,
                    tp_plan: Optional[Dict[int, Dict[str, Any]]] = None,
                    want: Optional[List[int]] = None,
                    seq_axis: Optional[str] = None,
                    data_axis: Optional[str] = None):
        """Run layers [lo, hi) on one microbatch. ``seed`` is the raw data
        array (lo == 0) or a {node_index: value} dict of carried nodes
        (stage_carried). Returns ``(out, stats)`` where ``out`` is the
        range's final node value, or {node_index: value} for the nodes in
        ``want`` when given (the carried set of the next boundary —
        cross-stage skips ride along). ``stats``: raw microbatch moments
        of any batch-stat layers (batch_norm) in the range — train only;
        the pipeline schedule accumulates these and the trainer applies
        one exact full-batch running-stat update after the ring.
        ``state`` is read-only (eval-time BN running stats)."""
        g = self.graph
        nodes: Dict[int, jax.Array] = {}
        if isinstance(seed, dict):
            nodes.update(seed)
        else:
            nodes[0] = seed
        sink: Dict[str, Any] = {}
        tp_plan = tp_plan or {}
        sharded: Dict[int, int] = {}   # node -> orig trailing width

        def slice_leaf(leaf, d, orig, me):
            """This shard's span of ``leaf`` along dim ``d``: zero-pad a
            non-divisible dim to a tp multiple first — pad rows/channels
            compute zeros that the eventual gather trims, and the
            pad+dynamic_slice pair transposes to exact zero-padded-slice
            gradients under autodiff."""
            span = -(-orig // tp_size)
            if span * tp_size != orig:
                pw = [(0, 0)] * leaf.ndim
                pw[d] = (0, span * tp_size - orig)
                leaf = jnp.pad(leaf, pw)
            return jax.lax.dynamic_slice_in_dim(leaf, me * span, span,
                                                axis=d)

        def gather_trim(v, orig):
            """Deferred manual-tp all-gather on the trailing channel axis,
            trimmed back to the original width (padding case) — a
            model-group-scoped collective every model peer of this stage
            executes (see tp_manual_plan)."""
            full = jax.lax.all_gather(v, tp_axis, axis=v.ndim - 1,
                                      tiled=True)
            if full.shape[-1] != orig:
                full = jax.lax.slice_in_dim(full, 0, orig, axis=-1)
            return full

        for li in range(lo, hi):
            spec, layer = g.layers[li], self.layers[li]
            # seq/data axes bound under the sequence-parallel pipeline:
            # mha takes the ring path, moe routes globally — collectives
            # scoped to this stage's seq/data peers, which all execute
            # the same switch branch
            ctx = ApplyCtx(train=train, rng=jax.random.fold_in(rng, li),
                           compute_dtype=self.compute_dtype,
                           stat_sink=sink if train else None,
                           seq_axis=seq_axis, data_axis=data_axis,
                           seq_gather_kv=seq_axis is not None)
            ent = tp_plan.get(li)
            if ent:
                # first channel-mixing consumer of a sharded chain:
                # materialize the full activation here
                for pos, orig in ent.get("gather", {}).items():
                    ni = spec.nindex_in[pos]
                    if ni in sharded:
                        nodes[ni] = gather_trim(nodes[ni], sharded.pop(ni))
            inputs = [nodes[ni] for ni in spec.nindex_in]
            lstate = (state or {}).get(layer.name, {})
            lparams = params.get(layer.name, {})
            if ent and ("params" in ent or "state" in ent):
                me = jax.lax.axis_index(tp_axis)
                if "params" in ent:
                    lparams = dict(lparams)
                    for key, (d, orig) in ent["params"].items():
                        lparams[key] = slice_leaf(lparams[key], d, orig, me)
                if "state" in ent and lstate:
                    lstate = dict(lstate)
                    for key, orig in ent["state"].items():
                        lstate[key] = slice_leaf(lstate[key], 0, orig, me)
            outputs, _ = layer.apply(lparams, lstate, inputs, ctx)
            if ent and "sink_gather" in ent and layer.name in sink:
                # batch-stat followers (BN) computed channel-local moments;
                # gather them back to full width so the trainer's post-ring
                # merge and the stats_sd probe see the unsharded shape
                sink[layer.name] = jax.tree_util.tree_map(
                    lambda v: gather_trim(v, ent["sink_gather"]),
                    sink[layer.name])
            if ent and "out_sharded" in ent:
                sharded[spec.nindex_out[0]] = ent["out_sharded"]
            for ni, out in zip(spec.nindex_out, outputs):
                nodes[ni] = out
        # stage end: every value leaving the stage (ring register, capture
        # banks, tail seeds) gathers to full width — sharded values never
        # cross stage boundaries (tp_manual_plan resets its walk per stage)
        if want is not None:
            return {ni: (gather_trim(nodes[ni], sharded[ni])
                         if ni in sharded else nodes[ni])
                    for ni in want}, sink
        ni = g.layers[hi - 1].nindex_out[0]
        out = nodes[ni]
        if ni in sharded:
            out = gather_trim(out, sharded[ni])
        return out, sink

    def apply_tail(self, body_hi: int, params: Params, state: NetState,
                   seeds: Dict[int, jax.Array],
                   label: Optional[jax.Array],
                   mask: jax.Array, rng: jax.Array,
                   train: bool,
                   label_slices: Optional[Dict[Tuple[int, int],
                                               jax.Array]] = None,
                   seq_axis: Optional[str] = None,
                   data_axis: Optional[str] = None,
                   want: Optional[List[int]] = None) -> ForwardResult:
        """Run the loss layers [body_hi, end) on the pipeline's output
        (they are row-wise, so GSPMD batch sharding applies). ``seeds``
        is a {node_index: value} dict: the top body node plus any other
        body node a tail layer reads (auxiliary loss heads —
        ``_tail_seeds``). ``want``: node indices whose POST-tail values
        the caller captures (metric bindings / extraction on nodes the
        tail writes) — returned in ``result.nodes`` keyed by index.
        ``label_slices``/``seq_axis``/``data_axis`` mirror ``apply`` for
        the sequence-parallel pipeline: pre-sliced width-sharded labels,
        and manual axes bound in the loss layers' ctx."""
        g = self.graph
        nodes: Dict[int, jax.Array] = dict(seeds)
        new_state: NetState = dict(state)
        total_loss = jnp.zeros((), jnp.float32)
        for li in range(body_hi, len(g.layers)):
            spec, layer = g.layers[li], self.layers[li]
            ctx = ApplyCtx(train=train, rng=jax.random.fold_in(rng, li),
                           compute_dtype=self.compute_dtype,
                           seq_axis=seq_axis, data_axis=data_axis)
            inputs = [nodes[ni] for ni in spec.nindex_in]
            outputs, lstate_out = layer.apply(
                params.get(layer.name, {}), new_state.get(layer.name, {}),
                inputs, ctx)
            if lstate_out:
                new_state[layer.name] = lstate_out
            for ni, out in zip(spec.nindex_out, outputs):
                nodes[ni] = out
            if layer.is_loss and (label is not None
                                  or label_slices is not None):
                a, b = g.label_slice(layer.target)
                lab = (label_slices[(a, b)] if label_slices is not None
                       else label[:, a:b])
                total_loss = total_loss + layer.loss(
                    outputs, lab.astype(jnp.float32), mask)
        out = nodes[g.layers[-1].nindex_out[0]]
        return ForwardResult(loss=total_loss, state=new_state,
                             nodes={ni: nodes[ni] for ni in want}
                             if want else None,
                             out=out)

    def node_value(self, result: ForwardResult, name: str) -> jax.Array:
        """Look up a captured node by name or 'top[-k]' style index."""
        assert result.nodes is not None, "apply(capture_nodes=True) required"
        return result.nodes[name]

    def param_shapes(self) -> Dict[str, Any]:
        """ShapeDtypeStruct tree of init()'s params (eval_shape — no
        values materialize), cached. The rule matcher and the FSDP
        planner key off this."""
        if self._param_shapes_cache is None:
            self._param_shapes_cache = jax.eval_shape(
                lambda: self.init(jax.random.PRNGKey(0))[0])
        return self._param_shapes_cache

    def partition_rules(self):
        """The per-model partition-rule table (parallel/rules.py):
        custom ``partition_rules`` config entries first (override
        wins), then ONE anchored rule per parameter leaf — spec from
        the layer type's declaration (``layer.param_pspecs``), P()
        (replicated) for everything else. ``(^|/)`` anchoring lets the
        same table cover optimizer state, whose momentum/moment trees
        mirror the params under "mom"/"m1"/"m2" prefixes — so params
        AND optimizer state shard from one declarative source."""
        import re as _re

        from jax.sharding import PartitionSpec as P

        from .parallel.rules import parse_rule_string, tree_paths
        rules = (parse_rule_string(self.sharding_cfg.partition_rules)
                 if self.sharding_cfg.partition_rules else [])
        # optimizer-state mirrors are the ONLY non-layer prefixes the
        # generated anchors admit — a bare (^|/) would let one layer's
        # rule capture a suffix of another layer's NESTED leaf (layer
        # 'o' vs 'attn1/o/wmat')
        opt = r"^(?:(?:mom|m1|m2)/)?"
        for spec, layer in zip(self.graph.layers, self.layers):
            if spec.is_shared or not layer.has_params:
                continue
            declared = dict(tree_paths(
                layer.param_pspecs() or {},
                is_leaf=lambda v: isinstance(v, tuple))[0])
            shapes = self.param_shapes().get(layer.name, {})
            for path, _leaf in tree_paths(shapes)[0]:
                ps = declared.get(path)
                rules.append((
                    opt + rf"{_re.escape(layer.name)}/{_re.escape(path)}$",
                    P(*ps) if ps is not None else P()))
        return rules

    def param_pspecs(self) -> Dict[str, Any]:
        """PartitionSpec tree matching init()'s params, derived from
        the partition-rule table (size-1 axes = replicated, so this is
        always safe to apply). The manual-tp plan and the trainer's
        placement both read THIS — one source of truth; the per-layer
        ``layer.param_pspecs`` declarations only feed the rule table
        (asserted equal in tests/test_partition_rules.py)."""
        if self._rule_pspecs_cache is None:
            from .parallel.rules import match_partition_rules
            self._rule_pspecs_cache = match_partition_rules(
                self.partition_rules(), self.param_shapes())
        return self._rule_pspecs_cache

    # -- introspection -----------------------------------------------------
    def param_tag(self, layer_name: str, param_name: str) -> str:
        """Tag used for lr/wd scoping: 'wmat' or 'bias'."""
        from .optim import tag_for_param
        return tag_for_param(param_name)

    def out_shape(self) -> Shape3:
        return self.node_shapes[self.graph.layers[-1].nindex_out[0]]

    def input_nhwc(self, batch: int) -> Tuple[int, int, int, int]:
        return to_nhwc(self.graph.input_shape, batch)
