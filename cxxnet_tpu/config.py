"""Config-file parser for the cxxnet key=value dialect.

Implements the same tokenizing grammar as the reference parser
(/root/reference/src/utils/config.h:20-192): whitespace-separated tokens,
``=`` as its own token, ``#`` line comments, ``"..."`` single-line quoted
strings with backslash escapes, and ``'...'`` multi-line quoted strings.
Order of key=value pairs is preserved because the net-config grammar is
order-sensitive (params attach to the preceding ``layer[...]`` line, iterator
sections run ``data = train`` .. ``iter = end``).

Unlike the reference (which silently stops parsing on a malformed token
stream), malformed input raises :class:`ConfigError`.

Validated config namespaces mostly live here (``serve_*``,
``telemetry_*``, ``io_retry_*``, ...); subsystem-owned namespaces
follow the same ``parse_*`` + ``known``-table contract next to the code
they parameterize — ``deploy_*`` in :mod:`cxxnet_tpu.deploy.policy`,
``elastic_*`` in the elastic package. graftlint's config-namespace pass
harvests every such table, wherever it lives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, List, Tuple

ConfigPairs = List[Tuple[str, str]]


class ConfigError(ValueError):
    """Raised on malformed config input."""


# -- mixed-precision compute policy ------------------------------------------

# accepted spellings of the ``compute_dtype`` config value
_DTYPE_NAMES = {
    "float32": "float32", "fp32": "float32", "f32": "float32",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "float16": "float16", "fp16": "float16", "f16": "float16",
}


@dataclasses.dataclass(frozen=True)
class Policy:
    """Mixed-precision compute policy threaded through the whole stack.

    ``param_dtype`` is the master-copy dtype: parameters and optimizer
    state always live in it (fp32), so checkpoints stay dtype-portable.
    ``compute_dtype`` is what activations/gradients flow in — each layer
    casts its fp32 params to it at apply time (one fused cast per step
    inside jit) and runs its matmul/conv in it. ``output_dtype`` is what
    leaves the model toward the outside world (serve responses, loss
    values, metric reductions) — fp32. Numerically sensitive interior
    math stays fp32 regardless of policy: batch/layer-norm statistics,
    softmax/cross-entropy, attention logits accumulation
    (``preferred_element_type``), and MoE router probabilities.

    The dtype fields hold jnp dtypes; use :func:`parse_policy` to build
    one from a config string.
    """
    param_dtype: Any
    compute_dtype: Any
    output_dtype: Any

    @property
    def reduced(self) -> bool:
        """True when compute runs below the fp32 master precision."""
        return self.compute_dtype != self.param_dtype

    @property
    def needs_loss_scale(self) -> bool:
        """fp16's ~6e-5 .. 65504 range underflows small gradients; bf16
        shares fp32's exponent range and needs no scaling."""
        import jax.numpy as jnp
        return self.compute_dtype == jnp.float16

    @property
    def compute_name(self) -> str:
        import jax.numpy as jnp
        return jnp.dtype(self.compute_dtype).name


# -- fused kernel selection ---------------------------------------------------

# accepted spellings of the ``fused_kernels`` config value -> canonical mode
_FUSED_MODES = {
    "auto": "auto", "": "auto",
    "1": "on", "on": "on", "true": "on", "yes": "on",
    "0": "off", "off": "off", "false": "off", "no": "off",
}


def parse_fused_mode(val: str) -> str:
    """Canonicalize the ``fused_kernels`` knob (doc/tasks.md "Fused
    kernels") to auto|on|off. ``auto`` selects the Pallas kernels on
    TPU backends only; ``on`` forces them everywhere (interpret mode
    off-TPU — the CPU test path); ``off`` is the escape hatch back to
    the jnp references. The same values are honored by the
    ``CXXNET_FUSED_KERNELS`` env override (ops/fused.py)."""
    canon = _FUSED_MODES.get(str(val).strip().lower())
    if canon is None:
        raise ConfigError(
            f"fused_kernels must be one of auto|1|0 (got {val!r})")
    return canon


# -- telemetry ----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """The ``telemetry_*`` knob set (doc/tasks.md "Telemetry"). Every
    field's zero value means OFF; an unconfigured run pays nothing."""
    trace_path: str = ""          # telemetry_trace: Chrome-trace JSON out
    trace_capacity: int = 65536   # telemetry_trace_capacity: span ring
    # -- distributed tracing (doc/tasks.md "Distributed tracing") ------
    trace_sample: float = 1.0     # telemetry_trace_sample: root fraction
    trace_tail_pct: float = 0.0   # telemetry_trace_tail_pct: exemplars
    trace_tail_window: int = 128  # telemetry_trace_tail_window: history
    trace_anchor_s: float = 30.0  # telemetry_trace_anchor_s: clock pairs
    sync_interval: int = 8        # telemetry_sync_interval: probe cadence
    port: int = 0                 # telemetry_port: standalone /metrics
    log_path: str = ""            # telemetry_log: JSONL snapshots
    log_interval_s: float = 5.0   # telemetry_log_interval (seconds)
    log_max_kb: int = 1024        # telemetry_log_max_kb: rotate beyond
    profile_steps: str = ""       # telemetry_profile_steps: "a-b"
    profile_dir: str = ""         # telemetry_profile_dir: xprof dump dir
    steptime: int = 1             # telemetry_steptime: 0 disables probe
    # -- fleet observability (doc/tasks.md "Fleet observability") -----
    ledger_path: str = ""         # telemetry_ledger: run-ledger JSONL
    run_id: str = ""              # telemetry_run_id: share across procs
    fleet_dir: str = ""           # telemetry_fleet_dir: snapshot push dir
    push_interval_s: float = 10.0  # telemetry_push_interval (seconds)
    host: int = -1                # telemetry_host: -1 = jax process index
    hang_s: float = 0.0           # telemetry_hang_s: 0 = watchdog off
    hang_dryrun: int = 0          # telemetry_hang_dryrun: 1 = one dump
    straggler_factor: float = 2.0  # telemetry_straggler_factor
    straggler_min_steps: int = 8  # telemetry_straggler_min_steps
    storm_window_s: float = 60.0  # telemetry_storm_window (seconds)
    storm_threshold: int = 8      # telemetry_storm_threshold


def parse_telemetry_config(cfg: ConfigPairs) -> TelemetryConfig:
    """Collect/validate the ``telemetry_*`` keys (last occurrence wins;
    unknown keys in the namespace fail fast, same contract as
    ``io_retry_*``)."""
    known = {
        "telemetry_trace": ("trace_path", str),
        "telemetry_trace_capacity": ("trace_capacity", int),
        "telemetry_trace_sample": ("trace_sample", float),
        "telemetry_trace_tail_pct": ("trace_tail_pct", float),
        "telemetry_trace_tail_window": ("trace_tail_window", int),
        "telemetry_trace_anchor_s": ("trace_anchor_s", float),
        "telemetry_sync_interval": ("sync_interval", int),
        "telemetry_port": ("port", int),
        "telemetry_log": ("log_path", str),
        "telemetry_log_interval": ("log_interval_s", float),
        "telemetry_log_max_kb": ("log_max_kb", int),
        "telemetry_profile_steps": ("profile_steps", str),
        "telemetry_profile_dir": ("profile_dir", str),
        "telemetry_steptime": ("steptime", int),
        "telemetry_ledger": ("ledger_path", str),
        "telemetry_run_id": ("run_id", str),
        "telemetry_fleet_dir": ("fleet_dir", str),
        "telemetry_push_interval": ("push_interval_s", float),
        "telemetry_host": ("host", int),
        "telemetry_hang_s": ("hang_s", float),
        "telemetry_hang_dryrun": ("hang_dryrun", int),
        "telemetry_straggler_factor": ("straggler_factor", float),
        "telemetry_straggler_min_steps": ("straggler_min_steps", int),
        "telemetry_storm_window": ("storm_window_s", float),
        "telemetry_storm_threshold": ("storm_threshold", int),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("telemetry_"):
            if name not in known:
                raise ConfigError(
                    f"unknown telemetry setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    tc = TelemetryConfig(**vals)
    if tc.trace_capacity < 1:
        raise ConfigError(
            f"telemetry_trace_capacity must be >= 1, got "
            f"{tc.trace_capacity}")
    if tc.sync_interval < 1:
        raise ConfigError(
            f"telemetry_sync_interval must be >= 1, got "
            f"{tc.sync_interval}")
    if not 0.0 <= tc.trace_sample <= 1.0:
        raise ConfigError(
            f"telemetry_trace_sample must be in [0, 1], got "
            f"{tc.trace_sample}")
    if not 0.0 <= tc.trace_tail_pct < 100.0:
        raise ConfigError(
            f"telemetry_trace_tail_pct must be in [0, 100) "
            f"(0 = keep every sampled trace), got {tc.trace_tail_pct}")
    if tc.trace_tail_window < 2:
        raise ConfigError(
            f"telemetry_trace_tail_window must be >= 2, got "
            f"{tc.trace_tail_window}")
    if tc.trace_anchor_s <= 0:
        raise ConfigError(
            f"telemetry_trace_anchor_s must be > 0, got "
            f"{tc.trace_anchor_s}")
    if tc.log_max_kb < 1:
        raise ConfigError(
            f"telemetry_log_max_kb must be >= 1, got {tc.log_max_kb}")
    if tc.log_interval_s <= 0:
        raise ConfigError(
            f"telemetry_log_interval must be > 0, got "
            f"{tc.log_interval_s}")
    if tc.push_interval_s <= 0:
        raise ConfigError(
            f"telemetry_push_interval must be > 0, got "
            f"{tc.push_interval_s}")
    if tc.hang_s < 0:
        raise ConfigError(
            f"telemetry_hang_s must be >= 0, got {tc.hang_s}")
    if tc.straggler_factor <= 1.0:
        raise ConfigError(
            f"telemetry_straggler_factor must be > 1, got "
            f"{tc.straggler_factor}")
    if tc.straggler_min_steps < 1:
        raise ConfigError(
            f"telemetry_straggler_min_steps must be >= 1, got "
            f"{tc.straggler_min_steps}")
    if tc.storm_window_s <= 0 or tc.storm_threshold < 1:
        raise ConfigError(
            "telemetry_storm_window must be > 0 and "
            "telemetry_storm_threshold >= 1, got "
            f"{tc.storm_window_s}/{tc.storm_threshold}")
    if tc.profile_steps:
        from .telemetry.profiler import parse_step_range
        try:
            parse_step_range(tc.profile_steps)
        except ValueError as e:
            raise ConfigError(str(e))
        if not tc.profile_dir:
            tc = dataclasses.replace(tc, profile_dir="./profile_dump")
    return tc


# -- serving ------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The ``serve_*`` knob set (doc/tasks.md "Serving" / "Serving at
    fleet scale"). One validated namespace, same contract as
    ``telemetry_*``: a typo'd key raises instead of silently serving
    with defaults."""
    port: int = 8080              # serve_port
    host: str = "127.0.0.1"       # serve_host
    buckets: str = ""             # serve_buckets: comma ladder ('' = auto)
    max_batch: int = 64           # serve_max_batch
    cache_size: int = 16          # serve_cache_size
    dtype: str = ""               # serve_dtype: compute-dtype override
    max_latency_ms: float = 5.0   # serve_max_latency_ms
    queue_rows: int = 1024        # serve_queue_rows
    timeout_ms: float = 0.0       # serve_timeout_ms (0 = none)
    log_interval_s: float = 30.0  # serve_log_interval
    breaker_threshold: int = 5    # serve_breaker_threshold (0 = off)
    breaker_reset_s: float = 10.0  # serve_breaker_reset_s
    degraded_queue_frac: float = 0.8  # serve_degraded_queue_frac
    slo_ms: float = 0.0           # serve_slo_ms (0 = SLO tracking off)
    slo_target: float = 0.99      # serve_slo_target
    slo_window_s: float = 60.0    # serve_slo_window_s
    slo_burn_degraded: float = 2.0  # serve_slo_burn_degraded
    # -- fleet (doc/tasks.md "Serving at fleet scale") -----------------
    replicas: int = 1             # serve_replicas: engines in the pool
    reload_s: float = 0.0         # serve_reload_s: ckpt poll (0 = off)
    ab: int = 0                   # serve_ab: 1 = reloads hit canaries only
    ab_replicas: int = 1          # serve_ab_replicas: canary subset size
    admission: int = 1            # serve_admission: 0 disables shedding
    drain_timeout_s: float = 30.0  # serve_drain_timeout_s: reload drain

    @property
    def fleet(self) -> bool:
        """Whether task_serve builds a replica pool (any fleet feature
        requested) instead of the plain single-engine path."""
        return self.replicas > 1 or self.reload_s > 0 or self.ab > 0


def parse_serve_config(cfg: ConfigPairs) -> ServeConfig:
    """Collect/validate the ``serve_*`` keys (last occurrence wins;
    unknown keys in the namespace fail fast)."""
    known = {
        "serve_port": ("port", int),
        "serve_host": ("host", str),
        "serve_buckets": ("buckets", str),
        "serve_max_batch": ("max_batch", int),
        "serve_cache_size": ("cache_size", int),
        "serve_dtype": ("dtype", str),
        "serve_max_latency_ms": ("max_latency_ms", float),
        "serve_queue_rows": ("queue_rows", int),
        "serve_timeout_ms": ("timeout_ms", float),
        "serve_log_interval": ("log_interval_s", float),
        "serve_breaker_threshold": ("breaker_threshold", int),
        "serve_breaker_reset_s": ("breaker_reset_s", float),
        "serve_degraded_queue_frac": ("degraded_queue_frac", float),
        "serve_slo_ms": ("slo_ms", float),
        "serve_slo_target": ("slo_target", float),
        "serve_slo_window_s": ("slo_window_s", float),
        "serve_slo_burn_degraded": ("slo_burn_degraded", float),
        "serve_replicas": ("replicas", int),
        "serve_reload_s": ("reload_s", float),
        "serve_ab": ("ab", int),
        "serve_ab_replicas": ("ab_replicas", int),
        "serve_admission": ("admission", int),
        "serve_drain_timeout_s": ("drain_timeout_s", float),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("serve_"):
            if name not in known:
                raise ConfigError(
                    f"unknown serve setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    sc = ServeConfig(**vals)
    if sc.replicas < 1:
        raise ConfigError(
            f"serve_replicas must be >= 1, got {sc.replicas}")
    if sc.max_batch < 1 or sc.queue_rows < 1 or sc.cache_size < 1:
        raise ConfigError(
            "serve_max_batch, serve_queue_rows and serve_cache_size "
            f"must be >= 1, got {sc.max_batch}/{sc.queue_rows}/"
            f"{sc.cache_size}")
    if sc.breaker_threshold < 0:
        raise ConfigError(
            f"serve_breaker_threshold must be >= 0, got "
            f"{sc.breaker_threshold}")
    if sc.reload_s < 0:
        raise ConfigError(
            f"serve_reload_s must be >= 0, got {sc.reload_s}")
    if sc.ab not in (0, 1):
        raise ConfigError(f"serve_ab must be 0 or 1, got {sc.ab}")
    if sc.ab_replicas < 1:
        raise ConfigError(
            f"serve_ab_replicas must be >= 1, got {sc.ab_replicas}")
    if sc.ab and sc.ab_replicas >= sc.replicas:
        raise ConfigError(
            f"serve_ab_replicas ({sc.ab_replicas}) must be < "
            f"serve_replicas ({sc.replicas}): A/B needs at least one "
            "replica left on the old version")
    if sc.slo_ms > 0 and not 0.0 < sc.slo_target < 1.0:
        raise ConfigError(
            f"serve_slo_target must be in (0, 1), got {sc.slo_target}")
    if sc.drain_timeout_s < 0:
        raise ConfigError(
            f"serve_drain_timeout_s must be >= 0, got "
            f"{sc.drain_timeout_s}")
    return sc


@dataclasses.dataclass(frozen=True)
class LMServeConfig:
    """The ``lm_serve_*`` / ``kv_*`` knob set (doc/tasks.md "LM
    serving"): paged KV-cache geometry plus the continuous-batching
    decode scheduler. Same validated-namespace contract as
    ``serve_*`` — a typo'd key raises instead of silently decoding
    with defaults."""
    kv_block_size: int = 16       # kv_block_size: tokens per cache block
    kv_pool_blocks: int = 64      # kv_pool_blocks: blocks in the pool
    kv_dtype: str = ""            # kv_dtype: cache dtype ('' = compute)
    max_seqs: int = 4             # lm_serve_max_seqs: decode batch rows
    max_context: int = 128        # lm_serve_max_context: prompt+gen cap
    max_new_tokens: int = 32      # lm_serve_max_new_tokens: default cap
    prefill_chunk: int = 16       # lm_serve_prefill_chunk: tokens/step
    max_queue: int = 32           # lm_serve_max_queue: waiting requests
    eos: int = -1                 # lm_serve_eos: stop token (-1 = none)
    role: str = "both"            # lm_serve_role: both|prefill|decode
    handoff_port: int = 0         # lm_serve_handoff_port (0 = ephemeral)
    deadline_ms: float = 0.0      # lm_serve_deadline_ms (0 = none)

    @property
    def max_blocks_per_seq(self) -> int:
        """Block-table width: blocks needed to hold ``max_context``
        tokens (every compiled shape uses this fixed T)."""
        return -(-self.max_context // self.kv_block_size)


def parse_lm_serve_config(cfg: ConfigPairs) -> LMServeConfig:
    """Collect/validate the ``lm_serve_*`` / ``kv_*`` keys (last
    occurrence wins; unknown keys in either namespace fail fast)."""
    known = {
        "kv_block_size": ("kv_block_size", int),
        "kv_pool_blocks": ("kv_pool_blocks", int),
        "kv_dtype": ("kv_dtype", str),
        "lm_serve_max_seqs": ("max_seqs", int),
        "lm_serve_max_context": ("max_context", int),
        "lm_serve_max_new_tokens": ("max_new_tokens", int),
        "lm_serve_prefill_chunk": ("prefill_chunk", int),
        "lm_serve_max_queue": ("max_queue", int),
        "lm_serve_eos": ("eos", int),
        "lm_serve_role": ("role", str),
        "lm_serve_handoff_port": ("handoff_port", int),
        "lm_serve_deadline_ms": ("deadline_ms", float),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("lm_serve_") or name.startswith("kv_"):
            if name not in known:
                raise ConfigError(
                    f"unknown lm-serve setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    lc = LMServeConfig(**vals)
    if lc.kv_block_size < 1 or lc.kv_pool_blocks < 2:
        raise ConfigError(
            "kv_block_size must be >= 1 and kv_pool_blocks >= 2 "
            "(block 0 is reserved scratch), got "
            f"{lc.kv_block_size}/{lc.kv_pool_blocks}")
    if lc.max_seqs < 1 or lc.max_queue < 1:
        raise ConfigError(
            "lm_serve_max_seqs and lm_serve_max_queue must be >= 1, "
            f"got {lc.max_seqs}/{lc.max_queue}")
    if lc.max_context < 1 or lc.max_new_tokens < 1:
        raise ConfigError(
            "lm_serve_max_context and lm_serve_max_new_tokens must be "
            f">= 1, got {lc.max_context}/{lc.max_new_tokens}")
    if lc.prefill_chunk < 1 or lc.prefill_chunk % lc.kv_block_size:
        raise ConfigError(
            f"lm_serve_prefill_chunk ({lc.prefill_chunk}) must be a "
            f"positive multiple of kv_block_size ({lc.kv_block_size}) "
            "so chunk boundaries align with cache blocks")
    if lc.role not in ("both", "prefill", "decode"):
        raise ConfigError(
            f"lm_serve_role must be both|prefill|decode, got {lc.role!r}")
    if lc.deadline_ms < 0:
        raise ConfigError(
            f"lm_serve_deadline_ms must be >= 0, got {lc.deadline_ms}")
    return lc


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """The ``quant_*`` / ``cascade_*`` knob set (doc/tasks.md
    "Quantized serving & cascade"): post-training int8 quantization
    calibration, the drift-verdict thresholds deploy gates on, and the
    two-tier confidence-cascade router. Same validated-namespace
    contract as ``serve_*`` — a typo'd key raises instead of silently
    serving with defaults."""
    calib_batches: int = 4        # quant_calib_batches: activation calib
    calib_percentile: float = 100.0  # quant_calib_percentile (100=absmax)
    max_rel_err: float = 0.05     # quant_max_rel_err: drift gate (RMS)
    max_sat_frac: float = 0.05    # quant_max_sat_frac: |q|==127 fraction
    parity_tol: float = 0.02      # quant_parity_tol: int8-vs-fp accuracy
    # -- cascade (two-tier confidence routing) -------------------------
    cascade_enable: int = 0       # cascade_enable: 1 = route via cascade
    cascade_threshold: float = 0.5  # cascade_threshold: escalate below
    cascade_metric: str = "margin"  # cascade_metric: margin|entropy
    cascade_model: str = ""       # cascade_model: fast-tier (quantized)
    #   checkpoint path ('' = derive by quantizing the flagship blob)
    cascade_replicas: int = 1     # cascade_replicas: fast-tier size


def parse_quant_config(cfg: ConfigPairs) -> QuantConfig:
    """Collect/validate the ``quant_*`` / ``cascade_*`` keys (last
    occurrence wins; unknown keys in either namespace fail fast)."""
    known = {
        "quant_calib_batches": ("calib_batches", int),
        "quant_calib_percentile": ("calib_percentile", float),
        "quant_max_rel_err": ("max_rel_err", float),
        "quant_max_sat_frac": ("max_sat_frac", float),
        "quant_parity_tol": ("parity_tol", float),
        "cascade_enable": ("cascade_enable", int),
        "cascade_threshold": ("cascade_threshold", float),
        "cascade_metric": ("cascade_metric", str),
        "cascade_model": ("cascade_model", str),
        "cascade_replicas": ("cascade_replicas", int),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("quant_") or name.startswith("cascade_"):
            if name not in known:
                raise ConfigError(
                    f"unknown quant/cascade setting {name!r}; valid "
                    "keys: " + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    qc = QuantConfig(**vals)
    if qc.calib_batches < 1:
        raise ConfigError(
            f"quant_calib_batches must be >= 1, got {qc.calib_batches}")
    if not 0.0 < qc.calib_percentile <= 100.0:
        raise ConfigError(
            "quant_calib_percentile must be in (0, 100], got "
            f"{qc.calib_percentile}")
    if qc.max_rel_err <= 0 or qc.max_sat_frac < 0:
        raise ConfigError(
            "quant_max_rel_err must be > 0 and quant_max_sat_frac "
            f">= 0, got {qc.max_rel_err}/{qc.max_sat_frac}")
    if qc.parity_tol <= 0:
        raise ConfigError(
            f"quant_parity_tol must be > 0, got {qc.parity_tol}")
    if qc.cascade_enable not in (0, 1):
        raise ConfigError(
            f"cascade_enable must be 0 or 1, got {qc.cascade_enable}")
    if not 0.0 < qc.cascade_threshold < 1.0:
        raise ConfigError(
            "cascade_threshold must be in (0, 1), got "
            f"{qc.cascade_threshold}")
    if qc.cascade_metric not in ("margin", "entropy"):
        raise ConfigError(
            f"cascade_metric must be margin|entropy, got "
            f"{qc.cascade_metric!r}")
    if qc.cascade_replicas < 1:
        raise ConfigError(
            f"cascade_replicas must be >= 1, got {qc.cascade_replicas}")
    return qc


# -- sharding -----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """The rule-driven sharding namespace (doc/tasks.md "Sharding
    rules"). One validated knob set, same contract as ``serve_*`` /
    ``telemetry_*``: a typo'd key raises instead of silently training
    with defaults."""
    partition_rules: str = ""   # custom rules PREPENDED to the model table
    fsdp_axis: str = ""         # mesh axis for at-rest param/opt sharding
    fsdp_min_size: int = 1024   # smallest leaf (elements) worth sharding


# mesh axes a config may name for FSDP-style at-rest sharding: the std
# (GSPMD dp/tp) step only — 'seq'/'pipe' are rejected because the sp/pp
# steps keep their own placement (and a size-1 axis would silently
# no-op, violating this namespace's fail-loud contract)
_FSDP_AXES = ("", "data", "model")


def parse_sharding_config(cfg: ConfigPairs) -> ShardingConfig:
    """Collect/validate ``partition_rules`` / ``fsdp_*`` keys (last
    occurrence wins; unknown keys in the namespace fail fast)."""
    known = {
        "partition_rules": ("partition_rules", str),
        "fsdp_axis": ("fsdp_axis", str),
        "fsdp_min_size": ("fsdp_min_size", int),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("fsdp_") or name.startswith("partition_rule"):
            if name not in known:
                raise ConfigError(
                    f"unknown sharding setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    sc = ShardingConfig(**vals)
    if sc.fsdp_axis not in _FSDP_AXES:
        raise ConfigError(
            f"fsdp_axis must be one of {'|'.join(a for a in _FSDP_AXES if a)}"
            f" (or unset), got {sc.fsdp_axis!r}")
    if sc.fsdp_min_size < 0:
        raise ConfigError(
            f"fsdp_min_size must be >= 0, got {sc.fsdp_min_size}")
    if sc.partition_rules:
        from .parallel.rules import parse_rule_string
        try:
            parse_rule_string(sc.partition_rules)
        except ValueError as e:
            raise ConfigError(f"bad partition_rules value: {e}")
    return sc


# -- checkpoint format + compile cache ----------------------------------------

@dataclasses.dataclass(frozen=True)
class CkptConfig:
    """The sharded-checkpoint / persistent-compile-cache knob set
    (doc/tasks.md "Sharded checkpointing"). One validated namespace,
    same contract as ``serve_*`` / ``telemetry_*``: a typo'd key raises
    instead of silently checkpointing in the wrong format."""
    shard_ckpt: int = 0          # shard_ckpt: 1 = rounds are shard SETS
    shard_ckpt_shards: int = 0   # shard_ckpt_shards: files per set
    #                              (0 = auto: one per jax process)
    compile_cache_dir: str = ""  # compile_cache_dir: persistent XLA
    #                              executable cache ('' = off)


def parse_ckpt_config(cfg: ConfigPairs) -> CkptConfig:
    """Collect/validate the ``shard_ckpt*`` / ``compile_cache_dir``
    keys (last occurrence wins; unknown keys in the namespace fail
    fast)."""
    known = {
        "shard_ckpt": ("shard_ckpt", int),
        "shard_ckpt_shards": ("shard_ckpt_shards", int),
        "compile_cache_dir": ("compile_cache_dir", str),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("shard_ckpt") or \
                name.startswith("compile_cache"):
            if name not in known:
                raise ConfigError(
                    f"unknown checkpoint setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    cc = CkptConfig(**vals)
    if cc.shard_ckpt not in (0, 1):
        raise ConfigError(
            f"shard_ckpt must be 0 or 1, got {cc.shard_ckpt}")
    if cc.shard_ckpt_shards < 0:
        raise ConfigError(
            f"shard_ckpt_shards must be >= 0 (0 = one per process), "
            f"got {cc.shard_ckpt_shards}")
    return cc


# -- elastic training ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """The ``elastic_*`` knob set (doc/tasks.md "Elastic training").
    One validated namespace, same contract as ``serve_*`` /
    ``telemetry_*``: a typo'd key raises instead of silently running a
    non-elastic (or wrongly-tuned) job. ``elastic_dir`` set = the train
    task runs as an elastic worker (membership + topology-change resume
    + preemption grace); unset = everything below is inert."""
    dir: str = ""                 # elastic_dir: shared membership dir
    heartbeat_s: float = 5.0      # elastic_heartbeat_s: liveness cadence
    grace_s: float = 10.0         # elastic_grace_s: SIGTERM notice window
    min_workers: int = 1          # elastic_min_workers: train floor
    worker: int = -1              # elastic_worker: -1 = telemetry host id
    capacity: int = 0             # elastic_capacity: dp this worker can
    #                               host (0 = its local device count)

    @property
    def enabled(self) -> bool:
        return bool(self.dir)


def parse_elastic_config(cfg: ConfigPairs) -> ElasticConfig:
    """Collect/validate the ``elastic_*`` keys (last occurrence wins;
    unknown keys in the namespace fail fast)."""
    known = {
        "elastic_dir": ("dir", str),
        "elastic_heartbeat_s": ("heartbeat_s", float),
        "elastic_grace_s": ("grace_s", float),
        "elastic_min_workers": ("min_workers", int),
        "elastic_worker": ("worker", int),
        "elastic_capacity": ("capacity", int),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("elastic_"):
            if name not in known:
                raise ConfigError(
                    f"unknown elastic setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    ec = ElasticConfig(**vals)
    if ec.heartbeat_s <= 0:
        raise ConfigError(
            f"elastic_heartbeat_s must be > 0, got {ec.heartbeat_s}")
    if ec.grace_s < 0:
        raise ConfigError(
            f"elastic_grace_s must be >= 0, got {ec.grace_s}")
    if ec.min_workers < 1:
        raise ConfigError(
            f"elastic_min_workers must be >= 1, got {ec.min_workers}")
    if ec.worker < -1:
        raise ConfigError(
            f"elastic_worker must be >= 0 (or -1 = auto), got "
            f"{ec.worker}")
    if ec.capacity < 0:
        raise ConfigError(
            f"elastic_capacity must be >= 0 (0 = local device count), "
            f"got {ec.capacity}")
    return ec


# -- input-data service -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DataServiceConfig:
    """The ``data_service*`` knob set (doc/tasks.md "Input data
    service"). One validated namespace, same contract as ``serve_*`` /
    ``telemetry_*``: a typo'd key raises instead of silently training
    off the local pipeline. ``data_service`` set = the train data
    section is served by the reader fleet (or, with the special value
    ``local``, by the same global-shuffle orchestration run
    in-process — the deterministic control / degrade stream); unset =
    everything below is inert."""
    endpoints: str = ""           # data_service: host:port[,host:port]|local
    shards: int = 0               # data_service_shards (0 = one/reader)
    seed: int = 0                 # data_service_seed: global shuffle seed
    cache_batches: int = 256      # data_service_cache: reader LRU frames
    readahead: int = 2            # data_service_readahead: decode-ahead
    timeout_ms: float = 5000.0    # data_service_timeout_ms: fetch timeout
    local_fallback: int = 1       # data_service_local_fallback: 0 = hard
    reader: int = -1              # data_service_reader: this reader's idx
    status_dir: str = ""          # data_service_status_dir: atomic status
    prefetch: int = 2             # data_service_prefetch: client batches
    #                               fetched ahead on a thread (0 = off)

    @property
    def enabled(self) -> bool:
        return bool(self.endpoints.strip())

    @property
    def local_only(self) -> bool:
        return self.endpoints.strip().lower() == "local"

    @property
    def endpoint_list(self) -> List[str]:
        if not self.enabled or self.local_only:
            return []
        return [e.strip() for e in self.endpoints.split(",") if e.strip()]

    @property
    def n_shards(self) -> int:
        return self.shards or max(1, len(self.endpoint_list))

    @staticmethod
    def split_endpoint(endpoint: str) -> Tuple[str, int]:
        host, _, port = endpoint.rpartition(":")
        return host, int(port)


def parse_data_service_config(cfg: ConfigPairs) -> DataServiceConfig:
    """Collect/validate the ``data_service*`` keys (last occurrence
    wins; unknown keys in the namespace fail fast)."""
    known = {
        "data_service": ("endpoints", str),
        "data_service_shards": ("shards", int),
        "data_service_seed": ("seed", int),
        "data_service_cache": ("cache_batches", int),
        "data_service_readahead": ("readahead", int),
        "data_service_timeout_ms": ("timeout_ms", float),
        "data_service_local_fallback": ("local_fallback", int),
        "data_service_reader": ("reader", int),
        "data_service_status_dir": ("status_dir", str),
        "data_service_prefetch": ("prefetch", int),
    }
    vals = {}
    for name, val in cfg:
        if name.startswith("data_service"):
            if name not in known:
                raise ConfigError(
                    f"unknown data_service setting {name!r}; valid "
                    "keys: " + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    dc = DataServiceConfig(**vals)
    if dc.enabled and not dc.local_only:
        for ep in dc.endpoint_list:
            host, _, port = ep.rpartition(":")
            if not host or not port.isdigit():
                raise ConfigError(
                    f"data_service endpoint {ep!r} is not host:port "
                    "(or the single value 'local')")
    if dc.shards < 0:
        raise ConfigError(
            f"data_service_shards must be >= 0 (0 = one per reader), "
            f"got {dc.shards}")
    if dc.cache_batches < 1:
        raise ConfigError(
            f"data_service_cache must be >= 1, got {dc.cache_batches}")
    if dc.readahead < 0:
        raise ConfigError(
            f"data_service_readahead must be >= 0, got {dc.readahead}")
    if dc.prefetch < 0:
        raise ConfigError(
            f"data_service_prefetch must be >= 0, got {dc.prefetch}")
    if dc.timeout_ms <= 0:
        raise ConfigError(
            f"data_service_timeout_ms must be > 0, got "
            f"{dc.timeout_ms}")
    if dc.local_fallback not in (0, 1):
        raise ConfigError(
            f"data_service_local_fallback must be 0 or 1, got "
            f"{dc.local_fallback}")
    if dc.reader < -1:
        raise ConfigError(
            f"data_service_reader must be >= 0 (or -1 = unset), got "
            f"{dc.reader}")
    if dc.enabled and dc.local_only and dc.shards < 1:
        raise ConfigError(
            "data_service = local needs an explicit "
            "data_service_shards >= 1 (there is no endpoint list to "
            "default the shard count from)")
    return dc


# -- model health -------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """The ``health_*`` knob set (doc/tasks.md "Model health"). One
    validated namespace, same contract as ``serve_*`` / ``telemetry_*``:
    a typo'd key raises instead of silently training unobserved.
    ``health = 1`` makes the train step compute compact per-layer
    numerics IN-TRACE (grad RMS/abs-max/finite-fraction, param RMS,
    update-to-weight ratio, activation abs-max / dead-ReLU fraction /
    BN batch-variance floor) that ride the step outputs and host-sync
    only every ``health_interval`` steps; ``health = 0`` (default) adds
    ZERO ops to the jaxpr and zero host syncs — the off path is
    byte-identical to a build that never heard of this namespace
    (pinned by tests/test_modelhealth.py)."""
    enabled: int = 0        # health: 1 = in-step model-health probe
    interval: int = 0       # health_interval: sync cadence in steps
    #                         (0 = follow sentinel_interval, default 8)
    window: int = 3         # health_window: consecutive bad syncs
    #                         before a detector emits health_advice
    dead_frac: float = 0.9  # health_dead_frac: dead-ReLU threshold
    bn_var_floor: float = 1e-8  # health_bn_var_floor: BN collapse
    ratio_min: float = 1e-8     # health_ratio_min: update/weight band
    ratio_max: float = 0.1      # health_ratio_max: update/weight band


def parse_health_config(cfg: ConfigPairs) -> HealthConfig:
    """Collect/validate the ``health`` / ``health_*`` keys (last
    occurrence wins; unknown keys in the namespace fail fast)."""
    known = {
        "health": ("enabled", int),
        "health_interval": ("interval", int),
        "health_window": ("window", int),
        "health_dead_frac": ("dead_frac", float),
        "health_bn_var_floor": ("bn_var_floor", float),
        "health_ratio_min": ("ratio_min", float),
        "health_ratio_max": ("ratio_max", float),
    }
    vals = {}
    for name, val in cfg:
        if name == "health" or name.startswith("health_"):
            if name not in known:
                raise ConfigError(
                    f"unknown health setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            field, conv = known[name]
            try:
                vals[field] = conv(val)
            except ValueError as e:
                raise ConfigError(f"bad {name} value {val!r}: {e}")
    hc = HealthConfig(**vals)
    if hc.enabled not in (0, 1):
        raise ConfigError(f"health must be 0 or 1, got {hc.enabled}")
    if hc.interval < 0:
        raise ConfigError(
            f"health_interval must be >= 0 (0 = sentinel_interval), "
            f"got {hc.interval}")
    if hc.window < 1:
        raise ConfigError(
            f"health_window must be >= 1, got {hc.window}")
    if not 0.0 < hc.dead_frac <= 1.0:
        raise ConfigError(
            f"health_dead_frac must be in (0, 1], got {hc.dead_frac}")
    if hc.bn_var_floor < 0:
        raise ConfigError(
            f"health_bn_var_floor must be >= 0, got {hc.bn_var_floor}")
    if not 0.0 <= hc.ratio_min < hc.ratio_max:
        raise ConfigError(
            "health_ratio_min must be >= 0 and < health_ratio_max, got "
            f"{hc.ratio_min}/{hc.ratio_max}")
    return hc


# -- IO retry policy ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff knobs for transient-IO retry (resilience.retry_call),
    applied by io/stream.py to every remote operation. Defaults: 4
    attempts, 50 ms -> 2 s full-jitter exponential backoff."""
    attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 1.0          # 0 = deterministic backoff, 1 = full jitter


def parse_retry_policy(cfg: ConfigPairs) -> RetryPolicy:
    """Build a :class:`RetryPolicy` from ``io_retry_attempts`` /
    ``io_retry_base_ms`` / ``io_retry_max_ms`` / ``io_retry_jitter``
    config keys (last occurrence wins, like every global key)."""
    known = {"io_retry_attempts", "io_retry_base_ms", "io_retry_max_ms",
             "io_retry_jitter"}
    vals = {}
    for name, val in cfg:
        if name.startswith("io_retry_"):
            if name not in known:
                # a typo'd retry knob silently falling back to defaults
                # is exactly the kind of quiet misconfiguration this
                # namespace check is cheap insurance against
                raise ConfigError(
                    f"unknown retry setting {name!r}; valid keys: "
                    + ", ".join(sorted(known)))
            vals[name] = val
    try:
        pol = RetryPolicy(
            attempts=int(vals.get("io_retry_attempts", "4")),
            base_delay_s=float(vals.get("io_retry_base_ms", "50")) / 1e3,
            max_delay_s=float(vals.get("io_retry_max_ms", "2000")) / 1e3,
            jitter=float(vals.get("io_retry_jitter", "1.0")))
    except ValueError as e:
        raise ConfigError(f"bad io_retry_* value: {e}")
    if pol.attempts < 1:
        raise ConfigError(
            f"io_retry_attempts must be >= 1, got {pol.attempts}")
    if not 0.0 <= pol.jitter <= 1.0:
        raise ConfigError(
            f"io_retry_jitter must be in [0, 1], got {pol.jitter}")
    return pol


def parse_policy(name: str) -> Policy:
    """``compute_dtype`` config value -> :class:`Policy` (fp32 masters and
    outputs, the named compute dtype in between)."""
    import jax.numpy as jnp
    canon = _DTYPE_NAMES.get(name.strip().lower())
    if canon is None:
        raise ConfigError(
            f"compute_dtype must be one of float32|bfloat16|float16 "
            f"(got {name!r})")
    compute = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
               "float16": jnp.float16}[canon]
    return Policy(param_dtype=jnp.float32, compute_dtype=compute,
                  output_dtype=jnp.float32)


class _Tokenizer:
    def __init__(self, text: str):
        self._text = text
        self._pos = 0
        self._line = 1

    def _getc(self) -> str:
        if self._pos >= len(self._text):
            return ""
        ch = self._text[self._pos]
        self._pos += 1
        if ch == "\n":
            self._line += 1
        return ch

    def tokens(self) -> Iterator[str]:
        """Yield raw tokens; ``=`` is always its own token."""
        ch = self._getc()
        tok: List[str] = []
        while ch:
            if ch == "#":
                while ch and ch not in "\r\n":
                    ch = self._getc()
                continue
            if ch in ('"', "'"):
                if tok:
                    raise ConfigError(
                        f"line {self._line}: token followed directly by string")
                quote = ch
                buf: List[str] = []
                ch = self._getc()
                while True:
                    if not ch:
                        raise ConfigError(f"line {self._line}: unterminated string")
                    if ch == "\\":
                        buf.append(self._getc())
                    elif ch == quote:
                        break
                    elif ch in "\r\n" and quote == '"':
                        raise ConfigError(f"line {self._line}: unterminated string")
                    else:
                        buf.append(ch)
                    ch = self._getc()
                yield "".join(buf)
                ch = self._getc()
                continue
            if ch == "=":
                if tok:
                    yield "".join(tok)
                    tok = []
                yield "="
                ch = self._getc()
                continue
            if ch in " \t\r\n":
                if tok:
                    yield "".join(tok)
                    tok = []
                ch = self._getc()
                continue
            tok.append(ch)
            ch = self._getc()
        if tok:
            yield "".join(tok)


def parse_config_string(text: str) -> ConfigPairs:
    """Parse config text into an ordered list of (name, value) pairs."""
    out: ConfigPairs = []
    toks = list(_Tokenizer(text).tokens())
    i = 0
    while i < len(toks):
        name = toks[i]
        if name == "=":
            raise ConfigError("expected parameter name, got '='")
        if i + 1 >= len(toks):
            raise ConfigError(f"dangling token {name!r} at end of config")
        if toks[i + 1] != "=":
            raise ConfigError(f"expected '=' after {name!r}")
        if i + 2 >= len(toks) or toks[i + 2] == "=":
            raise ConfigError(f"expected value after '{name} ='")
        out.append((name, toks[i + 2]))
        i += 3
    return out


def parse_config_file(path: str) -> ConfigPairs:
    from .io.stream import sopen
    with sopen(path, "rb") as f:
        return parse_config_string(f.read().decode("utf-8"))


def parse_cli_overrides(argv: List[str]) -> ConfigPairs:
    """Parse ``key=value`` command-line override arguments.

    Mirrors the reference CLI behavior (cxxnet_main.cpp:93-108): every arg
    containing ``=`` is appended after the config-file pairs so it wins for
    scalar settings.
    """
    out: ConfigPairs = []
    for arg in argv:
        if "=" not in arg:
            raise ConfigError(f"cannot parse CLI override {arg!r}; expected key=value")
        k, v = arg.split("=", 1)
        out.append((k.strip(), v.strip()))
    return out
