"""Config-file parser for the cxxnet key=value dialect.

Implements the same tokenizing grammar as the reference parser
(/root/reference/src/utils/config.h:20-192): whitespace-separated tokens,
``=`` as its own token, ``#`` line comments, ``"..."`` single-line quoted
strings with backslash escapes, and ``'...'`` multi-line quoted strings.
Order of key=value pairs is preserved because the net-config grammar is
order-sensitive (params attach to the preceding ``layer[...]`` line, iterator
sections run ``data = train`` .. ``iter = end``).

Unlike the reference (which silently stops parsing on a malformed token
stream), malformed input raises :class:`ConfigError`.
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

ConfigPairs = List[Tuple[str, str]]


class ConfigError(ValueError):
    """Raised on malformed config input."""


class _Tokenizer:
    def __init__(self, text: str):
        self._text = text
        self._pos = 0
        self._line = 1

    def _getc(self) -> str:
        if self._pos >= len(self._text):
            return ""
        ch = self._text[self._pos]
        self._pos += 1
        if ch == "\n":
            self._line += 1
        return ch

    def tokens(self) -> Iterator[str]:
        """Yield raw tokens; ``=`` is always its own token."""
        ch = self._getc()
        tok: List[str] = []
        while ch:
            if ch == "#":
                while ch and ch not in "\r\n":
                    ch = self._getc()
                continue
            if ch in ('"', "'"):
                if tok:
                    raise ConfigError(
                        f"line {self._line}: token followed directly by string")
                quote = ch
                buf: List[str] = []
                ch = self._getc()
                while True:
                    if not ch:
                        raise ConfigError(f"line {self._line}: unterminated string")
                    if ch == "\\":
                        buf.append(self._getc())
                    elif ch == quote:
                        break
                    elif ch in "\r\n" and quote == '"':
                        raise ConfigError(f"line {self._line}: unterminated string")
                    else:
                        buf.append(ch)
                    ch = self._getc()
                yield "".join(buf)
                ch = self._getc()
                continue
            if ch == "=":
                if tok:
                    yield "".join(tok)
                    tok = []
                yield "="
                ch = self._getc()
                continue
            if ch in " \t\r\n":
                if tok:
                    yield "".join(tok)
                    tok = []
                ch = self._getc()
                continue
            tok.append(ch)
            ch = self._getc()
        if tok:
            yield "".join(tok)


def parse_config_string(text: str) -> ConfigPairs:
    """Parse config text into an ordered list of (name, value) pairs."""
    out: ConfigPairs = []
    toks = list(_Tokenizer(text).tokens())
    i = 0
    while i < len(toks):
        name = toks[i]
        if name == "=":
            raise ConfigError("expected parameter name, got '='")
        if i + 1 >= len(toks):
            raise ConfigError(f"dangling token {name!r} at end of config")
        if toks[i + 1] != "=":
            raise ConfigError(f"expected '=' after {name!r}")
        if i + 2 >= len(toks) or toks[i + 2] == "=":
            raise ConfigError(f"expected value after '{name} ='")
        out.append((name, toks[i + 2]))
        i += 3
    return out


def parse_config_file(path: str) -> ConfigPairs:
    from .io.stream import sopen
    with sopen(path, "rb") as f:
        return parse_config_string(f.read().decode("utf-8"))


def parse_cli_overrides(argv: List[str]) -> ConfigPairs:
    """Parse ``key=value`` command-line override arguments.

    Mirrors the reference CLI behavior (cxxnet_main.cpp:93-108): every arg
    containing ``=`` is appended after the config-file pairs so it wins for
    scalar settings.
    """
    out: ConfigPairs = []
    for arg in argv:
        if "=" not in arg:
            raise ConfigError(f"cannot parse CLI override {arg!r}; expected key=value")
        k, v = arg.split("=", 1)
        out.append((k.strip(), v.strip()))
    return out
