"""Persistent XLA compile cache (doc/tasks.md "Sharded checkpointing").

Restart latency is the scale tax ROADMAP item 4 names: an elastic
takeover, a serve replica cold-start, or a plain resume pays checkpoint
restore PLUS a full recompile of every step/eval/serve executable. The
restore half is what the shard sets fix; this module removes the
recompile half by pointing JAX's persistent compilation cache at a
validated ``compile_cache_dir`` — the second process of a warm restart
loads serialized executables instead of re-running XLA.

Observability (the ``cxxnet_compile_cache`` tag): enabling lands a
``compile_cache`` ledger event and a ``cxxnet_compile_cache_info{dir}``
info-gauge; every persistent-cache hit counts into
``cxxnet_compile_cache_hits_total`` AND lands a
``compile_cache`` ledger event with ``hit=true``. That pairing is what
lets the PR-7 recompile-storm detector's operator distinguish
cold-start from storm: real XLA builds for a window are (compile
events - cache-hit events) — on jax builds where the
``backend_compile`` duration event wraps the cached path too (0.4.x),
``cxxnet_compiles_total`` alone over-counts a warm restart, while the
hits series climbing in lockstep marks the burst as cache-served
cold-start, not recompilation.
"""

from __future__ import annotations

import os
import threading

from .telemetry.ledger import LEDGER
from .telemetry.registry import REGISTRY

_LOCK = threading.Lock()
_ENABLED_DIR = ""
_HIT_LISTENER_INSTALLED = False


def enable_compile_cache(cache_dir: str, silent: bool = True) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    install the cache-hit counter. Idempotent (re-enable with the same
    dir is a no-op; a different dir re-points the cache). Returns False
    when this jax build has no compilation-cache config — the run
    proceeds uncached, degrade-don't-die like every observability
    path."""
    global _ENABLED_DIR
    if not cache_dir:
        return False
    cache_dir = os.path.abspath(cache_dir)
    with _LOCK:
        already = _ENABLED_DIR == cache_dir
    if already:
        return True
    import jax
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_enable_compilation_cache", True)
        # cache EVERY executable: the default min-compile-time gate
        # (1s) would skip exactly the many small serve-bucket / eval
        # executables whose recompile storm the detector measures
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:       # knob absent on some versions: fine
            pass
    except Exception as e:
        if not silent:
            print(f"compile cache: SKIP ({type(e).__name__}: {e}) — "
                  "this jax has no persistent compilation cache",
                  flush=True)
        return False
    with _LOCK:
        _ENABLED_DIR = cache_dir
    installed = _install_hit_listener()
    REGISTRY.gauge(
        "cxxnet_compile_cache_info",
        "Persistent compile cache identity (constant 1)",
        labels=("dir",)).labels(cache_dir).set(1)
    LEDGER.event("compile_cache", dir=cache_dir, enabled=True,
                 hit_counter=installed)
    if not silent:
        print(f"compile cache: persistent executables in {cache_dir}",
              flush=True)
    return True


def cache_dir() -> str:
    """The enabled cache directory ('' when off)."""
    with _LOCK:
        return _ENABLED_DIR


def _install_hit_listener() -> bool:
    """Count ``/jax/compilation_cache/cache_hits`` monitoring events
    into ``cxxnet_compile_cache_hits_total``. Idempotent; False when
    this jax has no monitoring listener API."""
    global _HIT_LISTENER_INSTALLED
    if _HIT_LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring
        register = monitoring.register_event_listener
    except Exception:
        return False
    c = REGISTRY.counter(
        "cxxnet_compile_cache_hits_total",
        "Persistent-compile-cache hits (executables NOT recompiled)")

    def _on_event(event: str, **kw) -> None:
        if event.endswith("compilation_cache/cache_hits"):
            c.inc()
            LEDGER.event("compile_cache", hit=True)

    try:
        register(_on_event)
    except Exception:
        return False
    _HIT_LISTENER_INSTALLED = True
    return True
