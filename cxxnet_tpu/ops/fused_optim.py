"""Fused multi-tensor optimizer apply (SGD / NAG / Adam) as Pallas
kernels.

The optimizer touches every byte of params + grads + momenta (+ Adam's
second moment) once per step — pure HBM traffic. The per-leaf pytree
walk in optim.py emits N independent elementwise chains (one per
parameter tensor: Inception-BN has ~200 leaves) that XLA schedules as
many small kernels with per-kernel launch and read/write bookkeeping;
this module instead packs each tag group's leaves into ONE flat f32
buffer per role and runs a single streaming Pallas kernel over it —
one fused pass per tag ("wmat"/"bias") instead of N per-leaf chains.

Trade-off, stated honestly: the pack (concat of raveled leaves) and
unpack (slice+reshape) around the opaque custom call are real extra
copies of the param-sized buffers that the per-leaf path does not pay,
so this trades O(params) extra bytes for O(#leaves) fewer kernel
launches. For convnet steps that is a favorable trade — param bytes
are ~1% of the flagship's activation-dominated step traffic while ~200
kernel launches are milliseconds of a ~55 ms step — but it is settled
by measurement, not assertion: the bench's ``hbm_bytes_per_step`` /
``per_step_ms`` carry the net effect, and ``fused_kernels = 0`` backs
it out if a model's params/activation ratio inverts the trade.

Semantics match optim._prep_grad + the per-leaf update exactly:
NaN-zeroing, gradient clip, weight decay, momentum/NAG or Adam with
bias correction (``lr_t`` precomputed host/trace-side — it is scalar
math). All leaves must be f32 (the master-weight dtype contract);
callers fall back to the per-leaf path otherwise.

Scalars (lr, momentum / lr_t) may be traced (the schedule is passed
into the step as traced scalars so LR changes never recompile) and
ride in as a tiny (1, 2) f32 operand.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .fused import HAVE_PALLAS, FusedSpmd, island, use_interpret

if HAVE_PALLAS:
    from jax.experimental import pallas as pl

_LANES = 128


def _pack(arrs: Sequence[jax.Array], block_rows: int):
    """Ravel + concat ``arrs`` into one (R, 128) f32 matrix, zero-padded
    to a whole number of (block_rows, 128) tiles. Returns (mat, total)."""
    flat = jnp.concatenate([jnp.ravel(a).astype(jnp.float32)
                            for a in arrs])
    total = flat.shape[0]
    tile = block_rows * _LANES
    padded = -(-total // tile) * tile
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    return flat.reshape(padded // _LANES, _LANES), total


def _unpack(mat: jax.Array, total: int, shapes, dtypes):
    flat = mat.reshape(-1)[:total]
    out, off = [], 0
    for s, d in zip(shapes, dtypes):
        n = 1
        for dim in s:
            n *= dim
        out.append(flat[off:off + n].reshape(s).astype(d))
        off += n
    return out


def _prep(g, w, wd, clip):
    """In-kernel analog of optim._prep_grad (NaN-zeroing, clip, wd)."""
    g = jnp.where(jnp.isnan(g), 0.0, g)
    if clip:
        g = jnp.clip(g, -clip, clip)
    if wd:
        g = g + wd * w
    return g


def _sgd_kernel(s_ref, w_ref, g_ref, m_ref, w_out, m_out, *,
                wd, clip, nag):
    lr = s_ref[0, 0]
    momentum = s_ref[0, 1]
    w = w_ref[...]
    m = m_ref[...]
    g = _prep(g_ref[...], w, wd, clip)
    new_m = momentum * m - lr * g
    if nag:       # nag_updater-inl.hpp:66-73
        w_out[...] = w + (1.0 + momentum) * new_m - momentum * m
    else:
        w_out[...] = w + new_m
    m_out[...] = new_m


def _adam_kernel(s_ref, w_ref, g_ref, m1_ref, m2_ref,
                 w_out, m1_out, m2_out, *, wd, clip, d1, d2):
    lr_t = s_ref[0, 0]
    w = w_ref[...]
    g = _prep(g_ref[...], w, wd, clip)
    n_m1 = m1_ref[...] + d1 * (g - m1_ref[...])
    n_m2 = m2_ref[...] + d2 * (g * g - m2_ref[...])
    w_out[...] = w - lr_t * n_m1 / (jnp.sqrt(n_m2) + 1e-8)
    m1_out[...] = n_m1
    m2_out[...] = n_m2


def _run(kern, scalars, mats, n_out, block_rows, interpret):
    rows = mats[0].shape[0]
    grid = (rows // block_rows,)
    row_spec = pl.BlockSpec((block_rows, _LANES), lambda j: (j, 0))
    s_spec = pl.BlockSpec((1, 2), lambda j: (0, 0))
    shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[s_spec] + [row_spec] * len(mats),
        out_specs=[row_spec] * n_out,
        out_shape=[shape] * n_out,
        interpret=interpret,
    )(scalars, *mats)


def fused_sgd_apply(ws: List[jax.Array], gs: List[jax.Array],
                    ms: List[jax.Array], lr, momentum, *,
                    wd: float, clip: float, nag: bool,
                    interpret: Optional[bool] = None,
                    block_rows: int = 256,
                    spmd: Optional[FusedSpmd] = None
                    ) -> Tuple[List[jax.Array], List[jax.Array]]:
    """One fused SGD/NAG momentum step over a whole tag group's leaves.
    Returns (new_ws, new_ms) with the input shapes/dtypes. With
    ``spmd`` the whole pack->kernel->unpack runs as a fully-replicated
    shard_map island: masters/grads/momenta are replicated on a dp
    mesh, every device computes the identical update, and GSPMD never
    meets the opaque pallas_call."""
    if spmd is not None:
        # lr/momentum may be traced schedule scalars: explicit island
        # inputs (replicated), never closure captures
        return island(
            spmd, lambda w_, g_, m_, lr_, mom_: fused_sgd_apply(
                w_, g_, m_, lr_, mom_, wd=wd, clip=clip, nag=nag,
                interpret=interpret, block_rows=block_rows),
            in_batch=(False,) * 5, out_batch=(False, False)
        )(list(ws), list(gs), list(ms), jnp.asarray(lr, jnp.float32),
          jnp.asarray(momentum, jnp.float32))
    shapes = [w.shape for w in ws]
    dtypes = [w.dtype for w in ws]
    wm, total = _pack(ws, block_rows)
    gm, _ = _pack(gs, block_rows)
    mm, _ = _pack(ms, block_rows)
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32),
                         jnp.asarray(momentum, jnp.float32)]).reshape(1, 2)
    kern = functools.partial(_sgd_kernel, wd=float(wd), clip=float(clip),
                             nag=bool(nag))
    nw, nm = _run(kern, scalars, [wm, gm, mm], 2, block_rows,
                  use_interpret(interpret))
    return (_unpack(nw, total, shapes, dtypes),
            _unpack(nm, total, shapes, dtypes))


def fused_adam_apply(ws: List[jax.Array], gs: List[jax.Array],
                     m1s: List[jax.Array], m2s: List[jax.Array], lr_t, *,
                     wd: float, clip: float, d1: float, d2: float,
                     interpret: Optional[bool] = None,
                     block_rows: int = 256,
                     spmd: Optional[FusedSpmd] = None):
    """One fused Adam step over a tag group (``lr_t`` already carries
    the bias correction). Returns (new_ws, new_m1s, new_m2s). With
    ``spmd``: fully-replicated shard_map island (see fused_sgd_apply)."""
    if spmd is not None:
        return island(
            spmd, lambda w_, g_, a_, b_, lr_: fused_adam_apply(
                w_, g_, a_, b_, lr_, wd=wd, clip=clip, d1=d1, d2=d2,
                interpret=interpret, block_rows=block_rows),
            in_batch=(False,) * 5, out_batch=(False, False, False)
        )(list(ws), list(gs), list(m1s), list(m2s),
          jnp.asarray(lr_t, jnp.float32))
    shapes = [w.shape for w in ws]
    dtypes = [w.dtype for w in ws]
    wm, total = _pack(ws, block_rows)
    gm, _ = _pack(gs, block_rows)
    m1m, _ = _pack(m1s, block_rows)
    m2m, _ = _pack(m2s, block_rows)
    scalars = jnp.stack([jnp.asarray(lr_t, jnp.float32),
                         jnp.zeros((), jnp.float32)]).reshape(1, 2)
    kern = functools.partial(_adam_kernel, wd=float(wd), clip=float(clip),
                             d1=float(d1), d2=float(d2))
    nw, nm1, nm2 = _run(kern, scalars, [wm, gm, m1m, m2m], 3, block_rows,
                        use_interpret(interpret))
    return (_unpack(nw, total, shapes, dtypes),
            _unpack(nm1, total, shapes, dtypes),
            _unpack(nm2, total, shapes, dtypes))
