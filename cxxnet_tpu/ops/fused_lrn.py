"""Fused cross-channel LRN: Pallas TPU kernels + jnp reference.

The classic cxxnet hand-fused CUDA kernel (lrn_layer-inl.hpp's chpool
expression) done TPU-natively: the jnp path materializes x^2, an
nsize-term shifted window sum, and the transcendental norm chain as
separate HBM-visible values (the optimization_barrier in
layers/conv.py even pins one on purpose), while this kernel holds one
(rows, C) tile in VMEM and does square, window-sum, powf, and the
final product in a single pass — one streaming read of x, one write
of y. The backward fuses the whole dx formula (including the
transposed-window term) into one kernel of its own, recomputing norm
from x in VMEM instead of saving it (HBM bytes are the scarce
resource, BENCH_r02–r04).

The channel window-sum is expressed as a matmul against a static
(C, C) band matrix — MXU-friendly, supported everywhere, and exact:
``win = x^2 @ B`` with ``B[i, c] = 1`` iff channel i falls in the
window centered at c. The backward needs the transposed window, so
``B^T`` rides along as a second constant input.

``fused_lrn`` returns y or ``None`` when the shape/dtype is
unsupported (caller falls back to the jnp reference).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, sublane_mult,
                    supported_dtype, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl


def lrn_reference(x: jax.Array, nsize: int, alpha: float, beta: float,
                  knorm: float) -> jax.Array:
    """Golden jnp implementation (layers/conv.py LRNLayer math, minus
    the fusion barrier — the kernel needs no fence)."""
    sq = jnp.square(x)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0),) * (x.ndim - 1)
                     + ((half, nsize - 1 - half),))
    c = x.shape[-1]
    win = sum(padded[..., i:i + c] for i in range(nsize))
    norm = knorm + (alpha / nsize) * win
    return x * jnp.exp(-beta * jnp.log(norm))


def band_matrix(c: int, nsize: int) -> np.ndarray:
    """(C, C) f32 window matrix: B[i, j] = 1 iff channel i is inside
    the centered window of output channel j."""
    half = nsize // 2
    i = np.arange(c)[:, None]
    j = np.arange(c)[None, :]
    return ((i >= j - half) & (i <= j + nsize - 1 - half)) \
        .astype(np.float32)


def _lrn_fwd_kernel(x_ref, band_ref, y_ref, *, ab, beta, knorm):
    xb = x_ref[...].astype(jnp.float32)
    win = jnp.dot(xb * xb, band_ref[...],
                  preferred_element_type=jnp.float32)
    norm = knorm + ab * win
    # norm**-beta as exp(-beta*log(norm)); norm >= knorm > 0
    y_ref[...] = (xb * jnp.exp(-beta * jnp.log(norm))).astype(y_ref.dtype)


def _lrn_bwd_kernel(x_ref, dy_ref, band_ref, bandt_ref, dx_ref, *,
                    ab, beta, knorm):
    """dx = dy * norm^-beta - 2*ab*beta * x * ((dy*x*norm^(-beta-1)) @ B^T)
    — norm recomputed in VMEM from x (one extra band matmul beats an
    HBM round trip for the saved norm)."""
    xb = x_ref[...].astype(jnp.float32)
    dyb = dy_ref[...].astype(jnp.float32)
    win = jnp.dot(xb * xb, band_ref[...],
                  preferred_element_type=jnp.float32)
    norm = knorm + ab * win
    p = jnp.exp(-beta * jnp.log(norm))            # norm^-beta
    t = dyb * xb * (p / norm)                     # dy*x*norm^(-beta-1)
    back = jnp.dot(t, bandt_ref[...], preferred_element_type=jnp.float32)
    dx_ref[...] = (dyb * p - 2.0 * ab * beta * xb * back) \
        .astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _lrn_2d(x2, band, bandt, ab, beta, knorm, interpret, bn):
    n, c = x2.shape
    nb = n // bn
    return pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, ab=ab, beta=beta, knorm=knorm),
        grid=(nb,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((c, c), lambda j: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
        interpret=interpret,
    )(x2, band)


def _lrn_fwd(x2, band, bandt, ab, beta, knorm, interpret, bn):
    return (_lrn_2d(x2, band, bandt, ab, beta, knorm, interpret, bn),
            (x2, band, bandt))


def _lrn_bwd(ab, beta, knorm, interpret, bn, res, dy):
    x2, band, bandt = res
    n, c = x2.shape
    nb = n // bn
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, ab=ab, beta=beta, knorm=knorm),
        grid=(nb,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((c, c), lambda j: (0, 0)),
                  pl.BlockSpec((c, c), lambda j: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
        interpret=interpret,
    )(x2, dy, band, bandt)
    # band/bandt are trace-time constants; zero cotangents (DCE'd)
    return dx, jnp.zeros_like(band), jnp.zeros_like(bandt)


_lrn_2d.defvjp(_lrn_fwd, _lrn_bwd)


def fused_lrn(x: jax.Array, nsize: int, alpha: float, beta: float,
              knorm: float, interpret: Optional[bool] = None,
              block_rows: int = 256,
              spmd: Optional[FusedSpmd] = None):
    """Fused LRN over the trailing channel axis of an NHWC node.
    Returns y (x.dtype) or ``None`` when unsupported. With ``spmd``
    the kernel runs as a shard_map island over the batch dim — LRN is
    row-local (the window runs over channels), so the island needs no
    collectives and its shard_map transpose is exact; the band
    matrices ride as closed-over constants."""
    if not HAVE_PALLAS or not supported_dtype(x):
        return None
    if x.ndim != 4 or knorm <= 0:
        return None
    c = x.shape[-1]
    n = x.size // c
    if c > 1024:          # (C, C) band must stay comfortably in VMEM
        return None
    if spmd is not None:
        if not batch_divisible(spmd, x.shape[0]):
            note_fallback("lrn_batch_indivisible")
            return None
        n_local = n // spmd.n_shards
    else:
        n_local = n
    target = max(8, min(block_rows, (1 << 20) // max(4 * c, 1) // 8 * 8))
    bn = row_block(n_local, target, mult=sublane_mult(x))
    if bn is None:
        if spmd is not None:
            note_fallback("lrn_shape")
        return None
    band = jnp.asarray(band_matrix(c, nsize))
    args = (band, band.T, float(alpha) / nsize, float(beta),
            float(knorm), use_interpret(interpret), bn)
    if spmd is not None:
        return island(
            spmd, lambda xl: _lrn_2d(xl.reshape(-1, c),
                                     *args).reshape(xl.shape),
            in_batch=(True,), out_batch=True)(x)
    y = _lrn_2d(x.reshape(n, c), *args)
    return y.reshape(x.shape)
