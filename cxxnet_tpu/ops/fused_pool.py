"""Fused pooling: Pallas TPU kernels + custom_vjp fused backward.

Two window regimes are fused (everything else falls back to the
layers' ``lax.reduce_window`` reference, doc/tasks.md "Fused kernels"):

* **tile** — non-overlapping square windows (``stride == kernel``, no
  padding, spatial dims divide): each input cell belongs to exactly one
  window, so the forward is a pure reshape-reduce and the backward is a
  single fused elementwise pass — no ``select-and-scatter`` (the
  notoriously expensive max-pool backward op on TPU). Covers the 2x2/2
  pools of the MNIST/bowl-class convnets.
* **global** — one window covering the whole spatial extent (the
  Inception-BN head's 7x7 global average pool): forward is a spatial
  mean/sum/max per (batch, channel), backward a broadcast.

Reducers: max / sum / avg (``scale_avg`` divides by kernel area
including padded cells — reference parity, here pad is 0 so it is just
1/k²). ``pre_relu`` folds relu_max_pooling's activation into the same
pass (max(relu(x)) on the forward; the backward masks out non-positive
cells, reproducing ``jax.nn.relu``'s zero-at-zero gradient exactly).

Max backward semantics match XLA's ``select-and-scatter`` reference:
the FIRST window cell (row-major over (dy, dx)) equal to the max gets
the whole cotangent — implemented as a statically unrolled first-match
sweep, capped at 16 cells (larger max windows fall back; avg/sum have
no per-cell scan and take any size).

Layout: x (B, H, W, C) is VIEWED as (B*oy, kh, ox, kw, C) — a pure
reshape since windows tile exactly — and blocked over the leading row
dim; the reduce runs over axes (1, 3) in VMEM.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, sublane_mult,
                    supported_dtype, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl

#: max windows larger than this fall back (the first-match sweep is a
#: statically unrolled per-cell loop)
MAX_FIRST_MATCH_CELLS = 16


def pool_reference(x: jax.Array, kh: int, kw: int, stride: int,
                   reducer: str, scale_avg: bool,
                   pre_relu: bool) -> jax.Array:
    """Golden jnp implementation — layers/conv.py's ``_PoolingLayer``
    math for the pad-0/extra-0 geometries this module fuses."""
    if pre_relu:
        x = jax.nn.relu(x)
    if reducer == "max":
        init, op = -jnp.inf, lax.max
    else:
        init, op = 0.0, lax.add
    y = lax.reduce_window(
        x, np.asarray(init, x.dtype), op,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0),) * 4)
    if scale_avg:
        y = y * (1.0 / (kh * kw))
    return y


# -- kernels ------------------------------------------------------------------

def _pool_fwd_kernel(x_ref, y_ref, *, reducer, pre_relu, scale):
    """x block (rb, kh, ox, kw, C) -> y block (rb, ox, C)."""
    x = x_ref[...]
    if pre_relu:
        x = jnp.maximum(x, 0)
    if reducer == "max":
        y = jnp.max(x, axis=(1, 3))
    else:
        y = jnp.sum(x, axis=(1, 3))
        if scale != 1.0:
            y = y * jnp.asarray(scale, y.dtype)
    y_ref[...] = y.astype(y_ref.dtype)


def _pool_bwd_max_kernel(x_ref, y_ref, dy_ref, dx_ref, *, kh, kw,
                         pre_relu):
    """First-match max backward: row-major (dy, dx) sweep; the first
    cell equal to the window max takes the whole cotangent (XLA
    select-and-scatter parity). ``pre_relu`` additionally masks cells
    that are not strictly positive (relu's zero-at-zero gradient)."""
    x = x_ref[...]
    xa = jnp.maximum(x, 0) if pre_relu else x
    ymax = y_ref[...]                       # (rb, ox, C)
    dyv = dy_ref[...]
    taken = jnp.zeros(ymax.shape, jnp.bool_)
    for dy in range(kh):
        for dx in range(kw):
            cell = xa[:, dy, :, dx, :]
            hit = jnp.logical_and(cell == ymax,
                                  jnp.logical_not(taken))
            if pre_relu:
                hit = jnp.logical_and(hit, x[:, dy, :, dx, :] > 0)
            taken = jnp.logical_or(taken, hit)
            dx_ref[:, dy, :, dx, :] = jnp.where(
                hit, dyv, jnp.zeros_like(dyv)).astype(dx_ref.dtype)


def _pool_bwd_lin_kernel(dy_ref, dx_ref, *, kh, kw, scale):
    """sum/avg backward: every window cell gets scale * dy."""
    dyv = dy_ref[...]
    if scale != 1.0:
        dyv = dyv * jnp.asarray(scale, dyv.dtype)
    out = jnp.broadcast_to(dyv[:, None, :, None, :],
                           dx_ref.shape)
    dx_ref[...] = out.astype(dx_ref.dtype)


# -- pallas_call wrappers -----------------------------------------------------

def _fwd_call(xr, reducer, pre_relu, scale, interpret, rb):
    n, kh, ox, kw, c = xr.shape
    kern = functools.partial(_pool_fwd_kernel, reducer=reducer,
                             pre_relu=pre_relu, scale=scale)
    return pl.pallas_call(
        kern,
        grid=(n // rb,),
        in_specs=[pl.BlockSpec((rb, kh, ox, kw, c),
                               lambda i: (i, 0, 0, 0, 0))],
        out_specs=pl.BlockSpec((rb, ox, c), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ox, c), xr.dtype),
        interpret=interpret,
    )(xr)


def _bwd_call(xr, y, dy, reducer, pre_relu, scale, interpret, rb):
    n, kh, ox, kw, c = xr.shape
    row5 = pl.BlockSpec((rb, kh, ox, kw, c), lambda i: (i, 0, 0, 0, 0))
    row3 = pl.BlockSpec((rb, ox, c), lambda i: (i, 0, 0))
    if reducer == "max":
        kern = functools.partial(_pool_bwd_max_kernel, kh=kh, kw=kw,
                                 pre_relu=pre_relu)
        return pl.pallas_call(
            kern, grid=(n // rb,),
            in_specs=[row5, row3, row3],
            out_specs=row5,
            out_shape=jax.ShapeDtypeStruct(xr.shape, xr.dtype),
            interpret=interpret,
        )(xr, y, dy)
    kern = functools.partial(_pool_bwd_lin_kernel, kh=kh, kw=kw,
                             scale=scale)
    return pl.pallas_call(
        kern, grid=(n // rb,),
        in_specs=[row3],
        out_specs=row5,
        out_shape=jax.ShapeDtypeStruct(xr.shape, xr.dtype),
        interpret=interpret,
    )(dy)


# -- custom_vjp ---------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _pool5(xr, reducer, pre_relu, scale, interpret, rb):
    return _fwd_call(xr, reducer, pre_relu, scale, interpret, rb)


def _pool5_fwd(xr, reducer, pre_relu, scale, interpret, rb):
    y = _fwd_call(xr, reducer, pre_relu, scale, interpret, rb)
    # max needs (x, max) residuals; sum/avg only x's SHAPE — the array
    # itself is never read by the linear backward kernel, so XLA DCEs
    # the residual's storage
    return y, (xr, y if reducer == "max" else None)


def _pool5_bwd(reducer, pre_relu, scale, interpret, rb, res, dy):
    xr, y = res
    dx = _bwd_call(xr, y, dy, reducer, pre_relu, scale, interpret, rb)
    return (dx,)


_pool5.defvjp(_pool5_fwd, _pool5_bwd)


def fused_pool(x: jax.Array, kh: int, kw: int, stride: int,
               pad: Tuple[int, int], extra: Tuple[int, int],
               reducer: str, scale_avg: bool, pre_relu: bool,
               interpret: Optional[bool] = None,
               block_rows: int = 64,
               spmd: Optional[FusedSpmd] = None) -> Optional[jax.Array]:
    """Fused pooling over an NHWC node, or ``None`` when the geometry
    is unsupported (caller runs its reduce_window reference):
    pad/extra must be 0 and windows must either tile exactly
    (stride == kh == kw, H % kh == 0, W % kw == 0) or be the single
    global window (kh == H and kw == W)."""
    if not HAVE_PALLAS or not supported_dtype(x) or x.ndim != 4:
        return None
    if reducer not in ("max", "sum"):
        return None
    if pad != (0, 0) or extra != (0, 0):
        return None
    b, h, w, c = x.shape
    if kh == h and kw == w:
        pass                                     # global single window
    elif not (stride == kh == kw and h % kh == 0 and w % kw == 0):
        return None
    if reducer == "max" and kh * kw > MAX_FIRST_MATCH_CELLS:
        return None
    oy, ox = h // kh if kh != h else 1, w // kw if kw != w else 1
    scale = 1.0 / (kh * kw) if scale_avg else 1.0
    n = b * oy
    if spmd is not None:
        if not batch_divisible(spmd, b):
            note_fallback("pool_batch_indivisible")
            return None
        n_local = n // spmd.n_shards
    else:
        n_local = n
    # VMEM budget: one (rb, kh, ox, kw, C) block + its output
    per_row = kh * ox * kw * c * max(x.dtype.itemsize, 2)
    target = max(8, min(block_rows, (1 << 20) // max(per_row, 1)
                        // 8 * 8))
    rb = row_block(n_local, target, mult=sublane_mult(x))
    if rb is None:
        if spmd is not None:
            note_fallback("pool_shape")
        return None
    itp = use_interpret(interpret)
    if spmd is not None:
        # pooling is row-local (windows never cross the batch dim):
        # collective-free island, exact shard_map transpose
        return island(
            spmd, lambda xl: _pool5(
                xl.reshape(-1, kh, ox, kw, c), reducer, pre_relu,
                float(scale), itp, rb
            ).reshape(xl.shape[0], oy, ox, c),
            in_batch=(True,), out_batch=True)(x)
    xr = x.reshape(n, kh, ox, kw, c)
    y = _pool5(xr, reducer, pre_relu, float(scale), itp, rb)
    return y.reshape(b, oy, ox, c)
