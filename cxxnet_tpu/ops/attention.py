"""Multi-head attention: jnp reference, chunked (online-softmax), and a
Pallas TPU flash-attention kernel.

The reference framework predates attention entirely (fixed 4-D image
tensors, /root/reference/src/layer/layer.h:33-39; SURVEY §5 "long-context:
N/A"), so this module is a TPU-idiomatic extension: it makes long-context
sequence models first-class. Three interchangeable implementations, all
taking (batch, seq, heads, head_dim) arrays:

* ``attention_reference`` — plain jnp softmax(QK^T)V; O(S^2) memory.
  The golden implementation every other path is tested against.
* ``chunked_attention`` — lax.scan over key/value blocks with the online
  softmax recurrence (running max / normalizer); O(S * block_k) live
  memory, differentiable through the scan, works on any backend. This is
  also the backward path for the flash kernel.
* ``flash_attention`` — Pallas kernels tiling q into MXU-friendly blocks
  and streaming k/v blocks through VMEM. The forward also emits the
  per-row logsumexp; the backward is FUSED (dq and dk/dv kernels that
  rebuild the softmax from that statistic — no second online pass, no
  chunked recompute). ``interpret=True`` runs the same kernels on CPU
  for tests. Not twice-differentiable (the fused backward is a kernel,
  not traced jnp); differentiate ``chunked_attention`` for higher-order
  uses.

Masking convention: ``causal=True`` masks strictly-future positions.
Fully-masked rows produce zeros (guarded divide), so ragged/padded
sequences are safe.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _scale(q: jax.Array, scale: Optional[float]) -> float:
    return (q.shape[-1] ** -0.5) if scale is None else scale


def rope(x: jax.Array, theta: float = 10000.0,
         offset=0) -> jax.Array:
    """Rotary position embedding on (B, S, H, D) (D even): rotates feature
    pairs by position-dependent angles, encoding relative positions
    directly in the q/k dot products. ``offset`` shifts the position base
    (for sequence-sharded shards; may be a traced scalar, e.g.
    lax.axis_index under shard_map)."""
    B, S, H, D = x.shape
    if D % 2:
        raise ValueError(f"rope needs an even head_dim, got {D}")
    pos = jnp.arange(S, dtype=jnp.float32) + jnp.asarray(offset, jnp.float32)
    inv = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    ang = pos[:, None] * inv[None, :]                 # (S, D/2)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., : D // 2], x[..., D // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def rope_at(x: jax.Array, theta: float, pos: jax.Array) -> jax.Array:
    """Rotary embedding at explicit per-token positions: ``x`` is
    (B, S, H, D), ``pos`` is an int array (B, S) of absolute positions.
    Element-for-element the same math as :func:`rope` (same ``pos * inv``
    products, same cos/sin combine), so a decode step that rotates one
    token at position ``p`` reproduces bit-for-bit what a full forward
    pass computed for that row — the property the paged KV-cache's
    greedy-decode parity contract rests on. Needed because ``rope``'s
    scalar ``offset`` cannot express a batch of sequences each at a
    different decode position."""
    B, S, H, D = x.shape
    if D % 2:
        raise ValueError(f"rope needs an even head_dim, got {D}")
    p = pos.astype(jnp.float32)
    inv = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    ang = p[:, :, None] * inv[None, None, :]          # (B, S, D/2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., : D // 2], x[..., D // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain softmax attention. q,k,v: (B, S, H, D) -> (B, S, H, D)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * _scale(q, scale)
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        ki = lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(qi >= ki, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    tables: jax.Array, q_pos: jax.Array,
                    lengths: jax.Array,
                    scale: Optional[float] = None) -> jax.Array:
    """Attention over a paged KV-cache (vLLM's PagedAttention shape,
    gather-style): each sequence's keys/values live in fixed-size token
    blocks of a shared pool, addressed by a per-sequence block table.

    q:        (B, Q, H, D) query tokens (Q=1 for a decode step, Q=chunk
              for prefill);
    k_pool /
    v_pool:   (N, bs, H, D) — N blocks of bs tokens each (block 0 is the
              caller's scratch block: padding rows write there and the
              masks below never read it as valid);
    tables:   (B, T) int32 — block ids; logical token ``i`` of sequence
              ``b`` lives at ``(tables[b, i // bs], i % bs)``;
    q_pos:    (B, Q) int32 absolute positions of the query tokens;
    lengths:  (B,) int32 valid tokens per sequence (0 = dead row).

    Masking is causal-by-position AND bounded by ``lengths`` (block-tail
    padding), mirroring ``attention_reference``'s -1e30 + softmax
    convention; logits accumulate in fp32 (preferred_element_type), so
    the output matches the reference path to fp32 tolerance. Per-row
    math depends only on that row's q/table/pool content — co-batched
    sequences cannot perturb each other, which is what makes
    iteration-level (continuous) batching bit-identical to the
    request-level path. Returns (B, Q, H, D)."""
    N, bs, H, D = k_pool.shape
    B, T = tables.shape
    kg = k_pool[tables].reshape(B, T * bs, H, D)
    vg = v_pool[tables].reshape(B, T * bs, H, D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kg,
                   preferred_element_type=jnp.float32) * _scale(q, scale)
    # gathered flat index IS the logical token position (ordered tables)
    k_pos = lax.broadcasted_iota(jnp.int32, (B, 1, 1, T * bs), 3)
    mask = (k_pos <= q_pos[:, None, :, None]) \
        & (k_pos < lengths[:, None, None, None])
    s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      vg.astype(p.dtype)).astype(q.dtype)


def gather_kv_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Sequence-parallel attention via ONE k/v all-gather per projection:
    local q shard attends over the gathered global k/v with global-position
    causal masking. Numerically identical to ring_attention; exists for the
    pipeline-parallel composition, where the ring's collective_permute is
    unsafe inside a stage's switch branch (its rendezvous is global across
    the mesh on the CPU runtime — devices in other stages never arrive)
    while all_gather participation is subgroup-scoped. Costs O(S_global)
    k/v bytes per shard instead of the ring's O(S_local) residency.
    q,k,v: (B, S_local, H, D) -> (B, S_local, H, D)."""
    kg = lax.all_gather(k, axis_name, axis=1, tiled=True)
    vg = lax.all_gather(v, axis_name, axis=1, tiled=True)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kg,
                   preferred_element_type=jnp.float32) * _scale(q, scale)
    if causal:
        off = lax.axis_index(axis_name) * q.shape[1]
        qi = lax.broadcasted_iota(jnp.int32, s.shape, 2) + off
        ki = lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(qi >= ki, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      vg.astype(p.dtype)).astype(q.dtype)


def _online_block_update(acc, m, l, q, kb, vb, q_pos, k_pos, scale, causal,
                         k_valid_upto=None):
    """One online-softmax accumulation step against key/value block (kb, vb).

    acc: (B,H,Sq,D) f32, m/l: (B,H,Sq) f32; q: (B,Sq,H,D);
    kb/vb: (B,Sk,H,D); q_pos: (Sq,), k_pos: (Sk,) global positions.
    ``k_valid_upto`` masks key positions >= that bound (block tail padding)
    independently of the causal mask.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                   preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    if k_valid_upto is not None:
        valid = (k_pos < k_valid_upto)[None, :]
        mask = valid if mask is None else jnp.logical_and(mask, valid)
    if mask is not None:
        mask = mask[None, None]
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp under the new running max; explicitly zero masked entries so a
    # fully-masked block contributes nothing (avoids exp(-NEG+NEG)=1)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
    return acc_new, m_new, l_new


def chunked_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = False, scale: Optional[float] = None,
                      block_k: int = 128) -> jax.Array:
    """Online-softmax attention scanning over k/v blocks (B,S,H,D)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    sc = _scale(q, scale)
    block_k = min(block_k, Sk)
    nb = -(-Sk // block_k)
    pad = nb * block_k - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(Sq)

    def step(carry, blk):
        acc, m, l = carry
        j, kj, vj = blk
        k_pos = j * block_k + jnp.arange(block_k)
        acc, m, l = _online_block_update(
            acc, m, l, q, kj, vj, q_pos, k_pos, sc, causal,
            k_valid_upto=Sk if pad else None)
        return (acc, m, l), None

    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    (acc, m, l), _ = lax.scan(step, (acc0, m0, l0),
                              (jnp.arange(nb), kb, vb))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# -- Pallas flash attention ---------------------------------------------------

def _block_causal_mask(qi, kj, block_q, block_k):
    """Causal keep-mask for one (q-block, k-block) tile — shared by the
    forward and both backward kernels so the masking convention cannot
    drift between them."""
    qpos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = kj * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qpos >= kpos


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      scale, causal, block_q, block_k):
    """One (batch*head, q-block, k-block) grid cell. K/V truly stream: each
    cell sees only one (block_k, D) K/V tile in VMEM; the online-softmax
    accumulators persist in VMEM scratch across the (innermost, sequential)
    k-block grid dimension, so VMEM residency is O(block) not O(S).
    Also emits the per-row logsumexp — the statistic the fused backward
    kernels rebuild the softmax from without a second online pass.
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, D)
        kb = k_ref[0].astype(jnp.float32)         # (block_k, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _block_causal_mask(qi, kj, block_q, block_k)
            s = jnp.where(mask, s, _NEG)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new

    if causal:
        # skip tiles strictly above the causal diagonal
        @pl.when(kj * block_k <= qi * block_q + block_q - 1)
        def _guarded():
            compute()
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _finish():
        l_fin = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_fin[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m_ref[:, 0] + jnp.log(l_fin)


try:  # pallas import kept lazy-safe: CPU-only installs still get chunked
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   with_lse: bool = False):
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_attention: seq lengths ({Sq},{Sk}) must be divisible by "
            f"blocks ({block_q},{block_k})")
    # (B,S,H,D) -> (B*H, S, D): one grid row per (batch, head)
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    kern = functools.partial(
        _flash_fwd_kernel, scale=_scale(q, scale), causal=causal,
        block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kern,
        grid=(B * H, Sq // block_q, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            # (BH, Sq, 1): trailing dims (block_q, 1) satisfy the TPU
            # (8, 128)-divisible-or-full block constraint
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running normalizer
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    return (out, lse) if with_lse else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention (B,S,H,D): Pallas forward, chunked-recompute backward.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    kernel is exercised in CPU tests (the pairtest spirit, SURVEY §4).
    """
    if not _HAVE_PALLAS:   # promised fallback for pallas-less installs
        return chunked_attention(q, k, v, causal=causal, scale=scale,
                                 block_k=block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    """dQ_i = scale * sum_j dS_ij K_j, dS = P o (dP - delta); P rebuilt
    from the saved logsumexp (no second online pass). Grid
    (batch*head, q-block, k-block sequential)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, D)
        kb = k_ref[0].astype(jnp.float32)         # (block_k, D)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)        # (block_q, D)
        lse = lse_ref[0, :, 0]                    # (block_q,)
        delta = delta_ref[0, :, 0]                # (block_q,)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[:, None])
        if causal:
            # explicit zeroing: fully-masked rows carry a sentinel lse,
            # where exp(s - lse) would NOT vanish on its own
            p = jnp.where(_block_causal_mask(qi, kj, block_q, block_k),
                          p, 0.0)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[...] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kj * block_k <= qi * block_q + block_q - 1)
        def _guarded():
            compute()
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          scale, causal, block_q, block_k):
    """dK_j = scale * sum_i dS_ij^T Q_i; dV_j = sum_i P_ij^T dO_i. Grid
    (batch*head, k-block, q-block sequential)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, D)
        kb = k_ref[0].astype(jnp.float32)         # (block_k, D)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(_block_causal_mask(qi, kj, block_q, block_k),
                          p, 0.0)
        dv_acc[...] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    if causal:
        # only q blocks at or below the diagonal contribute to this k tile
        @pl.when(qi * block_q + block_q - 1 >= kj * block_k)
        def _guarded():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret):
    """Fused Pallas backward: dq from one kernel, dk/dv from another,
    both rebuilding the softmax from the forward's logsumexp."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    sc = _scale(q, scale)
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    dot = g.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise precompute
    delta = jnp.sum(dot.astype(jnp.float32)
                    * out.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
                    .astype(jnp.float32), axis=-1)[..., None]

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    r_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=sc, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B * H, Sq // block_q, Sk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # swapped grid: (bh, k-block, q-block) — index maps swap i/j roles
    q_spec2 = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    r_spec2 = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=sc, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B * H, Sk // block_k, Sq // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    unflat = lambda a, S: a.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    return unflat(dq, Sq), unflat(dk, Sk), unflat(dv, Sk)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    if not _HAVE_PALLAS:
        out = chunked_attention(q, k, v, causal=causal, scale=scale,
                                block_k=block_k)
        return out, (q, k, v, None, None)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if not _HAVE_PALLAS:
        # fall back to differentiating the chunked implementation
        def f(q_, k_, v_):
            return chunked_attention(q_, k_, v_, causal=causal, scale=scale,
                                     block_k=block_k)
        _, vjp = jax.vjp(f, q, k, v)
        return vjp(g)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_backward(q, k, v, out, lse, g, causal, scale,
                           block_q, block_k, interpret)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
