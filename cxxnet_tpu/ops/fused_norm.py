"""Fused BatchNorm + activation: Pallas TPU kernels + jnp reference.

The flagship Inception-BN step is memory-bound (BENCH_r02–r04:
roofline_pct ~100–105% at arith_intensity ~64), and its dominant
non-conv HBM traffic is the conv -> batch_norm -> relu chain: the jnp
path reads the conv output for the moments, again for the normalize,
and writes the normalized activation, with the relu riding a fourth
logical pass XLA must fuse back in. The fused kernel does moments,
normalize, scale/shift, and the activation in ONE ``pallas_call``
whose HBM traffic is exactly two streaming reads of x plus one write
of y — the minimum any batch-norm can do (the mean must exist before
the first output byte) — and the backward rebuilds x_hat from saved
(mean, rstd) residuals in one fused pass of its own (two reads of
x/dy + one write of dx) instead of the 5+ reduction/elementwise
kernels the autodiff graph schedules.

Layout: activations are viewed as (N, C) rows — N = batch*H*W for
conv nodes, N = batch for flat nodes — with per-channel statistics
reduced over rows. The row dimension is tiled (``fused.row_block``);
the channel dimension stays whole in VMEM (C is at most a few
thousand for every shipped config).

Variance options (the ADVICE r5 fold-in):

* ``two_pass=False`` (default, reference parity): one-pass
  E[x^2]-E[x]^2 with a clamp at 0 — grid of 2 row-sweeps.
* ``two_pass=True``: numerically-robust E[(x-mean)^2] — grid of 3
  row-sweeps (one extra streaming read of x, no cancellation risk).

``fused_bn_act`` returns ``(y, mean, var)`` or ``None`` when the
shape/dtype is unsupported (caller falls back to its jnp reference).
``mean``/``var`` feed the layer's running-stat EMA only and are
treated as non-differentiable by the custom_vjp (their cotangents are
structurally zero: no loss reads them).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, sublane_mult,
                    supported_dtype, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def bn_act_reference(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                     eps: float, act: str = "none",
                     two_pass: bool = False):
    """Golden jnp implementation on NHWC/flat nodes: returns
    ``(y, mean, var)`` with f32 per-channel stats over all leading
    axes, matching layers/norm.py's training math exactly."""
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    if two_pass:
        var = jnp.mean(jnp.square(xf - mean), axis=axes)
    else:
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)
    out = (x - mean) * inv * gamma + beta
    if act == "relu":
        out = jax.nn.relu(out)
    return out.astype(x.dtype), mean, var


# -- forward kernel -----------------------------------------------------------

def _bn_fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref,
                   acc1, acc2, *, nb, n_total, eps, act, two_pass):
    """Row-sweep phases over grid (2*nb,) or (3*nb,) — the x BlockSpec
    maps every phase back onto the same nb row blocks, so x streams
    through VMEM once per sweep while the (1, C) accumulators persist
    in scratch across the whole grid (flash-attention pattern)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)

    if two_pass:
        @pl.when(j < nb)
        def _sum():
            xb = x_ref[...].astype(jnp.float32)
            acc1[...] += jnp.sum(xb, axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _mean():
            acc1[...] = acc1[...] / n_total        # acc1 becomes mean

        @pl.when(jnp.logical_and(j >= nb, j < 2 * nb))
        def _sumsq():
            d = x_ref[...].astype(jnp.float32) - acc1[...]
            acc2[...] += jnp.sum(d * d, axis=0, keepdims=True)

        @pl.when(j == 2 * nb - 1)
        def _finish_stats():
            var = acc2[...] / n_total
            mean_ref[...] = acc1[...]
            var_ref[...] = var
            acc2[...] = jax.lax.rsqrt(var + eps)   # acc2 becomes rstd
        norm_from = 2 * nb
    else:
        @pl.when(j < nb)
        def _sums():
            xb = x_ref[...].astype(jnp.float32)
            acc1[...] += jnp.sum(xb, axis=0, keepdims=True)
            acc2[...] += jnp.sum(xb * xb, axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _finish_stats2():
            mean = acc1[...] / n_total
            # one-pass E[x^2]-E[x]^2, clamped at 0 (f32 cancellation
            # can push it a hair negative) — layers/norm.py parity
            var = jnp.maximum(acc2[...] / n_total - mean * mean, 0.0)
            mean_ref[...] = mean
            var_ref[...] = var
            acc1[...] = mean
            acc2[...] = jax.lax.rsqrt(var + eps)   # acc2 becomes rstd
        norm_from = nb

    @pl.when(j >= norm_from)
    def _normalize():
        xb = x_ref[...].astype(jnp.float32)
        out = ((xb - acc1[...]) * acc2[...]
               * gamma_ref[...].astype(jnp.float32)
               + beta_ref[...].astype(jnp.float32))
        if act == "relu":
            out = jnp.maximum(out, 0.0)
        y_ref[...] = out.astype(y_ref.dtype)


def _bn_forward(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    n, c = x2.shape
    nb = n // bn
    sweeps = 3 if two_pass else 2
    kern = functools.partial(
        _bn_fwd_kernel, nb=nb, n_total=float(n), eps=eps, act=act,
        two_pass=two_pass)
    row_spec = pl.BlockSpec((bn, c), lambda j: (j % nb, 0))
    vec_spec = pl.BlockSpec((1, c), lambda j: (0, 0))
    y, mean, var = pl.pallas_call(
        kern,
        grid=(sweeps * nb,),
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, c), x2.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=interpret,
    )(x2, gamma.reshape(1, c), beta.reshape(1, c))
    return y, mean, var


# -- backward kernel ----------------------------------------------------------

def _bn_bwd_kernel(*refs, nb, n_total, act):
    """Two row sweeps: (1) reduce sum(dy') and sum(dy'*x_hat) per
    channel (dy' = dy masked by the activation), (2) the fused dx
    formula. dgamma/dbeta fall out of the phase-1 reductions."""
    if act == "relu":
        (x_ref, dy_ref, y_ref, gamma_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref, sb, sxh) = refs
    else:
        (x_ref, dy_ref, gamma_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref, sb, sxh) = refs
        y_ref = None
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        sb[...] = jnp.zeros_like(sb)
        sxh[...] = jnp.zeros_like(sxh)

    def _dyp_xhat():
        dyb = dy_ref[...].astype(jnp.float32)
        if y_ref is not None:
            dyb = jnp.where(y_ref[...].astype(jnp.float32) > 0.0, dyb, 0.0)
        xh = ((x_ref[...].astype(jnp.float32) - mean_ref[...])
              * rstd_ref[...])
        return dyb, xh

    @pl.when(j < nb)
    def _reduce():
        dyb, xh = _dyp_xhat()
        sb[...] += jnp.sum(dyb, axis=0, keepdims=True)
        sxh[...] += jnp.sum(dyb * xh, axis=0, keepdims=True)

    @pl.when(j == nb - 1)
    def _grads():
        dgamma_ref[...] = sxh[...]
        dbeta_ref[...] = sb[...]

    @pl.when(j >= nb)
    def _dx():
        dyb, xh = _dyp_xhat()
        g = gamma_ref[...].astype(jnp.float32) * rstd_ref[...]
        dx = g * (dyb - sb[...] / n_total - xh * (sxh[...] / n_total))
        dx_ref[...] = dx.astype(dx_ref.dtype)


def _bn_backward(x2, gamma, mean, rstd, y2, dy2, act, interpret, bn):
    n, c = x2.shape
    nb = n // bn
    kern = functools.partial(_bn_bwd_kernel, nb=nb, n_total=float(n),
                             act=act)
    row_spec = pl.BlockSpec((bn, c), lambda j: (j % nb, 0))
    vec_spec = pl.BlockSpec((1, c), lambda j: (0, 0))
    ins = [x2, dy2] + ([y2] if act == "relu" else [])
    ins += [gamma.reshape(1, c), mean, rstd]
    in_specs = [row_spec, row_spec] + \
        ([row_spec] if act == "relu" else []) + [vec_spec] * 3
    dx, dgamma, dbeta = pl.pallas_call(
        kern,
        grid=(2 * nb,),
        in_specs=in_specs,
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, c), x2.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=interpret,
    )(*ins)
    return dx, dgamma, dbeta


# -- custom_vjp wrapper -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _bn_act_2d(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    y, mean, var = _bn_forward(x2, gamma, beta, eps, act, two_pass,
                               interpret, bn)
    return y, mean, var


def _bn_act_fwd(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    y, mean, var = _bn_forward(x2, gamma, beta, eps, act, two_pass,
                               interpret, bn)
    rstd = jax.lax.rsqrt(var + eps)
    res = (x2, gamma, mean, rstd, y if act == "relu" else None)
    return (y, mean, var), res


def _bn_act_bwd(eps, act, two_pass, interpret, bn, res, cts):
    # cts = (dy, dmean, dvar); mean/var feed the running-stat EMA only
    # (carried state, never read by the loss), so their cotangents are
    # structurally zero and are dropped here — same contract as
    # flash_attention's lse output.
    x2, gamma, mean, rstd, y2 = res
    dy = cts[0]
    dx, dgamma, dbeta = _bn_backward(x2, gamma, mean, rstd, y2, dy, act,
                                     interpret, bn)
    return (dx, dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype))


_bn_act_2d.defvjp(_bn_act_fwd, _bn_act_bwd)


# -- mesh (shard_map island) variant ------------------------------------------
#
# On a dp mesh the single fused kernel cannot stand: its moments would
# be shard-local where the jnp path's jnp.mean is a cross-replica
# sync-BN collective, and GSPMD cannot shard the opaque pallas_call
# anyway. The mesh variant splits the moment pass from the normalize
# pass around an explicit psum over the data axis, all inside one
# fully-manual shard_map island: per shard the HBM traffic is still
# two streaming reads of x plus one write of y (the single-device
# minimum), and the psum'd sums make fused BN on a dp mesh match the
# global-moment jnp reference bit-for-bit in fp32 whenever the sums
# themselves are exact (integer-valued activations; pinned by
# tests/test_fused_mesh.py) and to f32 rounding otherwise. The
# backward's cross-shard reductions (dgamma/dbeta and the dx formula's
# sum terms) psum the same way. custom_vjp sits OUTSIDE the islands —
# fwd and bwd are each their own shard_map — so autodiff never
# transposes a shard_map (whose 0.4.x transpose rules the psum'd
# replicated outputs would confuse).

def _bn_sums_kernel(x_ref, s1_ref, s2_ref, acc1, acc2, *, nb):
    """One streaming read: per-channel local (sum, sum of squares)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)
    xb = x_ref[...].astype(jnp.float32)
    acc1[...] += jnp.sum(xb, axis=0, keepdims=True)
    acc2[...] += jnp.sum(xb * xb, axis=0, keepdims=True)

    @pl.when(j == nb - 1)
    def _finish():
        s1_ref[...] = acc1[...]
        s2_ref[...] = acc2[...]


def _bn_norm_kernel(x_ref, gamma_ref, beta_ref, mean_ref, rstd_ref,
                    y_ref, *, act):
    """Second read + the write: normalize/scale/shift (+relu) with the
    (already global) mean/rstd handed in as (1, C) rows."""
    xb = x_ref[...].astype(jnp.float32)
    out = ((xb - mean_ref[...]) * rstd_ref[...]
           * gamma_ref[...].astype(jnp.float32)
           + beta_ref[...].astype(jnp.float32))
    if act == "relu":
        out = jnp.maximum(out, 0.0)
    y_ref[...] = out.astype(y_ref.dtype)


def _bn_bwd_sums_kernel(*refs, nb, act):
    """Local backward reductions: per-channel sum(dy') and
    sum(dy'*x_hat), dy' masked by the activation."""
    if act == "relu":
        x_ref, dy_ref, y_ref, mean_ref, rstd_ref, sb_ref, sxh_ref, \
            ab, axh = refs
    else:
        x_ref, dy_ref, mean_ref, rstd_ref, sb_ref, sxh_ref, ab, axh = refs
        y_ref = None
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        ab[...] = jnp.zeros_like(ab)
        axh[...] = jnp.zeros_like(axh)
    dyb = dy_ref[...].astype(jnp.float32)
    if y_ref is not None:
        dyb = jnp.where(y_ref[...].astype(jnp.float32) > 0.0, dyb, 0.0)
    xh = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * rstd_ref[...]
    ab[...] += jnp.sum(dyb, axis=0, keepdims=True)
    axh[...] += jnp.sum(dyb * xh, axis=0, keepdims=True)

    @pl.when(j == nb - 1)
    def _finish():
        sb_ref[...] = ab[...]
        sxh_ref[...] = axh[...]


def _bn_bwd_dx_kernel(*refs, act):
    """dx from the fused formula, with the mean-normalized GLOBAL
    reduction terms (sb/n, sxh/n) handed in as (1, C) rows."""
    if act == "relu":
        (x_ref, dy_ref, y_ref, gamma_ref, mean_ref, rstd_ref,
         sbn_ref, sxhn_ref, dx_ref) = refs
    else:
        (x_ref, dy_ref, gamma_ref, mean_ref, rstd_ref,
         sbn_ref, sxhn_ref, dx_ref) = refs
        y_ref = None
    dyb = dy_ref[...].astype(jnp.float32)
    if y_ref is not None:
        dyb = jnp.where(y_ref[...].astype(jnp.float32) > 0.0, dyb, 0.0)
    xh = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * rstd_ref[...]
    g = gamma_ref[...].astype(jnp.float32) * rstd_ref[...]
    dx = g * (dyb - sbn_ref[...] - xh * sxhn_ref[...])
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _row_vec_specs(bn, c):
    return (pl.BlockSpec((bn, c), lambda j: (j, 0)),
            pl.BlockSpec((1, c), lambda j: (0, 0)))


def _mesh_fwd_local(x, gamma, beta, *, c, eps, act, interpret, bn, axis,
                    n_total):
    """Island body (local shard): pallas sums -> psum -> global
    moments -> pallas normalize."""
    x2 = x.reshape(-1, c)
    n, _ = x2.shape
    nb = n // bn
    row, vec = _row_vec_specs(bn, c)
    s1, s2 = pl.pallas_call(
        functools.partial(_bn_sums_kernel, nb=nb),
        grid=(nb,), in_specs=[row], out_specs=[vec, vec],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32)] * 2,
        interpret=interpret)(x2)
    s1 = jax.lax.psum(s1, axis)
    s2 = jax.lax.psum(s2, axis)
    mean = s1 / n_total
    # one-pass E[x^2]-E[x]^2 with the same clamp as the jnp reference
    var = jnp.maximum(s2 / n_total - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    y2 = pl.pallas_call(
        functools.partial(_bn_norm_kernel, act=act),
        grid=(nb,), in_specs=[row, vec, vec, vec, vec], out_specs=row,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret)(x2, gamma.reshape(1, c),
                             beta.reshape(1, c), mean, rstd)
    return (y2.reshape(x.shape), mean.reshape(c), var.reshape(c),
            rstd.reshape(c))


def _mesh_bwd_local(x, dy, y, gamma, mean, rstd, *, c, act, interpret,
                    bn, axis, n_total):
    """Island body (local shard): pallas reductions -> psum -> pallas
    dx; dgamma/dbeta are the psum'd (global) reductions."""
    x2 = x.reshape(-1, c)
    dy2 = dy.reshape(-1, c)
    n, _ = x2.shape
    nb = n // bn
    row, vec = _row_vec_specs(bn, c)
    mean_r, rstd_r = mean.reshape(1, c), rstd.reshape(1, c)
    ins = [x2, dy2] + ([y.reshape(-1, c)] if act == "relu" else []) \
        + [mean_r, rstd_r]
    in_specs = [row, row] + ([row] if act == "relu" else []) + [vec, vec]
    sb, sxh = pl.pallas_call(
        functools.partial(_bn_bwd_sums_kernel, nb=nb, act=act),
        grid=(nb,), in_specs=in_specs, out_specs=[vec, vec],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32)] * 2,
        interpret=interpret)(*ins)
    sb = jax.lax.psum(sb, axis)
    sxh = jax.lax.psum(sxh, axis)
    ins2 = [x2, dy2] + ([y.reshape(-1, c)] if act == "relu" else []) \
        + [gamma.reshape(1, c), mean_r, rstd_r, sb / n_total,
           sxh / n_total]
    in_specs2 = [row, row] + ([row] if act == "relu" else []) \
        + [vec] * 5
    dx2 = pl.pallas_call(
        functools.partial(_bn_bwd_dx_kernel, act=act),
        grid=(nb,), in_specs=in_specs2, out_specs=row,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret)(*ins2)
    return dx2.reshape(x.shape), sxh.reshape(c), sb.reshape(c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _bn_act_mesh(x, gamma, beta, eps, act, interpret, bn, spmd, n_total):
    y, mean, var, _ = island(
        spmd, functools.partial(
            _mesh_fwd_local, c=x.shape[-1], eps=eps, act=act,
            interpret=interpret, bn=bn, axis=spmd.batch_axis,
            n_total=n_total),
        in_batch=(True, False, False),
        out_batch=(True, False, False, False))(x, gamma, beta)
    return y, mean, var


def _bn_act_mesh_fwd(x, gamma, beta, eps, act, interpret, bn, spmd,
                     n_total):
    y, mean, var, rstd = island(
        spmd, functools.partial(
            _mesh_fwd_local, c=x.shape[-1], eps=eps, act=act,
            interpret=interpret, bn=bn, axis=spmd.batch_axis,
            n_total=n_total),
        in_batch=(True, False, False),
        out_batch=(True, False, False, False))(x, gamma, beta)
    res = (x, gamma, mean, rstd, y if act == "relu" else None)
    return (y, mean, var), res


def _bn_act_mesh_bwd(eps, act, interpret, bn, spmd, n_total, res, cts):
    # mean/var cotangents are structurally zero (EMA-only outputs),
    # exactly as on the single-device path
    x, gamma, mean, rstd, y = res
    dy = cts[0]
    if y is None:
        y = dy          # placeholder with the right sharding; unread
    dx, dgamma, dbeta = island(
        spmd, functools.partial(
            _mesh_bwd_local, c=x.shape[-1], act=act, interpret=interpret,
            bn=bn, axis=spmd.batch_axis, n_total=n_total),
        in_batch=(True, True, True, False, False, False),
        out_batch=(True, False, False))(x, dy, y, gamma, mean, rstd)
    return (dx, dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype))


_bn_act_mesh.defvjp(_bn_act_mesh_fwd, _bn_act_mesh_bwd)


def fused_bn_act(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                 eps: float, act: str = "none", two_pass: bool = False,
                 interpret: Optional[bool] = None,
                 block_rows: int = 256,
                 spmd: Optional[FusedSpmd] = None):
    """Fused train-time batch norm (+ optional relu) over the trailing
    channel axis of an NHWC or flat node. Returns ``(y, mean, var)``
    with y in x.dtype and f32 stats, or ``None`` when unsupported
    (caller falls back to the jnp reference). With ``spmd`` the op
    runs as a shard_map island on the mesh — moments are psum'd over
    the data axis (sync-BN) so the math matches the GSPMD jnp path."""
    if not HAVE_PALLAS or not supported_dtype(x):
        return None
    if x.ndim != 4 or act not in ("none", "relu"):
        return None
    c = x.shape[-1]
    n = x.size // c
    if spmd is not None:
        if two_pass:
            # the mesh islands implement the default one-pass moments
            # only; bn_two_pass falls back to the (sync-BN) jnp path
            note_fallback("bn_two_pass_mesh")
            return None
        if not batch_divisible(spmd, x.shape[0]):
            note_fallback("bn_batch_indivisible")
            return None
        n_local = n // spmd.n_shards
    else:
        n_local = n
    # keep ~2 row blocks + accumulators comfortably inside VMEM even
    # for wide flat nodes: shrink the row tile as C grows
    target = max(8, min(block_rows, (1 << 20) // max(4 * c, 1) // 8 * 8))
    bn = row_block(n_local, target, mult=sublane_mult(x))
    if bn is None or gamma.shape != (c,) or beta.shape != (c,):
        if spmd is not None:
            note_fallback("bn_shape")
        return None
    if spmd is not None:
        y, mean, var = _bn_act_mesh(x, gamma, beta, float(eps), act,
                                    use_interpret(interpret), bn, spmd,
                                    float(n))
        return y, mean, var
    x2 = x.reshape(n, c)
    y, mean, var = _bn_act_2d(x2, gamma, beta, float(eps), act,
                              bool(two_pass), use_interpret(interpret), bn)
    return y.reshape(x.shape), mean.reshape(c), var.reshape(c)
