"""Fused BatchNorm + activation: Pallas TPU kernels + jnp reference.

The flagship Inception-BN step is memory-bound (BENCH_r02–r04:
roofline_pct ~100–105% at arith_intensity ~64), and its dominant
non-conv HBM traffic is the conv -> batch_norm -> relu chain: the jnp
path reads the conv output for the moments, again for the normalize,
and writes the normalized activation, with the relu riding a fourth
logical pass XLA must fuse back in. The fused kernel does moments,
normalize, scale/shift, and the activation in ONE ``pallas_call``
whose HBM traffic is exactly two streaming reads of x plus one write
of y — the minimum any batch-norm can do (the mean must exist before
the first output byte) — and the backward rebuilds x_hat from saved
(mean, rstd) residuals in one fused pass of its own (two reads of
x/dy + one write of dx) instead of the 5+ reduction/elementwise
kernels the autodiff graph schedules.

Layout: activations are viewed as (N, C) rows — N = batch*H*W for
conv nodes, N = batch for flat nodes — with per-channel statistics
reduced over rows. The row dimension is tiled (``fused.row_block``);
the channel dimension stays whole in VMEM (C is at most a few
thousand for every shipped config).

Variance options (the ADVICE r5 fold-in):

* ``two_pass=False`` (default, reference parity): one-pass
  E[x^2]-E[x]^2 with a clamp at 0 — grid of 2 row-sweeps.
* ``two_pass=True``: numerically-robust E[(x-mean)^2] — grid of 3
  row-sweeps (one extra streaming read of x, no cancellation risk).

``fused_bn_act`` returns ``(y, mean, var)`` or ``None`` when the
shape/dtype is unsupported (caller falls back to its jnp reference).
``mean``/``var`` feed the layer's running-stat EMA only and are
treated as non-differentiable by the custom_vjp (their cotangents are
structurally zero: no loss reads them).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .fused import (HAVE_PALLAS, row_block, sublane_mult,
                    supported_dtype, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def bn_act_reference(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                     eps: float, act: str = "none",
                     two_pass: bool = False):
    """Golden jnp implementation on NHWC/flat nodes: returns
    ``(y, mean, var)`` with f32 per-channel stats over all leading
    axes, matching layers/norm.py's training math exactly."""
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    if two_pass:
        var = jnp.mean(jnp.square(xf - mean), axis=axes)
    else:
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)
    out = (x - mean) * inv * gamma + beta
    if act == "relu":
        out = jax.nn.relu(out)
    return out.astype(x.dtype), mean, var


# -- forward kernel -----------------------------------------------------------

def _bn_fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref,
                   acc1, acc2, *, nb, n_total, eps, act, two_pass):
    """Row-sweep phases over grid (2*nb,) or (3*nb,) — the x BlockSpec
    maps every phase back onto the same nb row blocks, so x streams
    through VMEM once per sweep while the (1, C) accumulators persist
    in scratch across the whole grid (flash-attention pattern)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)

    if two_pass:
        @pl.when(j < nb)
        def _sum():
            xb = x_ref[...].astype(jnp.float32)
            acc1[...] += jnp.sum(xb, axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _mean():
            acc1[...] = acc1[...] / n_total        # acc1 becomes mean

        @pl.when(jnp.logical_and(j >= nb, j < 2 * nb))
        def _sumsq():
            d = x_ref[...].astype(jnp.float32) - acc1[...]
            acc2[...] += jnp.sum(d * d, axis=0, keepdims=True)

        @pl.when(j == 2 * nb - 1)
        def _finish_stats():
            var = acc2[...] / n_total
            mean_ref[...] = acc1[...]
            var_ref[...] = var
            acc2[...] = jax.lax.rsqrt(var + eps)   # acc2 becomes rstd
        norm_from = 2 * nb
    else:
        @pl.when(j < nb)
        def _sums():
            xb = x_ref[...].astype(jnp.float32)
            acc1[...] += jnp.sum(xb, axis=0, keepdims=True)
            acc2[...] += jnp.sum(xb * xb, axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _finish_stats2():
            mean = acc1[...] / n_total
            # one-pass E[x^2]-E[x]^2, clamped at 0 (f32 cancellation
            # can push it a hair negative) — layers/norm.py parity
            var = jnp.maximum(acc2[...] / n_total - mean * mean, 0.0)
            mean_ref[...] = mean
            var_ref[...] = var
            acc1[...] = mean
            acc2[...] = jax.lax.rsqrt(var + eps)   # acc2 becomes rstd
        norm_from = nb

    @pl.when(j >= norm_from)
    def _normalize():
        xb = x_ref[...].astype(jnp.float32)
        out = ((xb - acc1[...]) * acc2[...]
               * gamma_ref[...].astype(jnp.float32)
               + beta_ref[...].astype(jnp.float32))
        if act == "relu":
            out = jnp.maximum(out, 0.0)
        y_ref[...] = out.astype(y_ref.dtype)


def _bn_forward(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    n, c = x2.shape
    nb = n // bn
    sweeps = 3 if two_pass else 2
    kern = functools.partial(
        _bn_fwd_kernel, nb=nb, n_total=float(n), eps=eps, act=act,
        two_pass=two_pass)
    row_spec = pl.BlockSpec((bn, c), lambda j: (j % nb, 0))
    vec_spec = pl.BlockSpec((1, c), lambda j: (0, 0))
    y, mean, var = pl.pallas_call(
        kern,
        grid=(sweeps * nb,),
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, c), x2.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=interpret,
    )(x2, gamma.reshape(1, c), beta.reshape(1, c))
    return y, mean, var


# -- backward kernel ----------------------------------------------------------

def _bn_bwd_kernel(*refs, nb, n_total, act):
    """Two row sweeps: (1) reduce sum(dy') and sum(dy'*x_hat) per
    channel (dy' = dy masked by the activation), (2) the fused dx
    formula. dgamma/dbeta fall out of the phase-1 reductions."""
    if act == "relu":
        (x_ref, dy_ref, y_ref, gamma_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref, sb, sxh) = refs
    else:
        (x_ref, dy_ref, gamma_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref, sb, sxh) = refs
        y_ref = None
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        sb[...] = jnp.zeros_like(sb)
        sxh[...] = jnp.zeros_like(sxh)

    def _dyp_xhat():
        dyb = dy_ref[...].astype(jnp.float32)
        if y_ref is not None:
            dyb = jnp.where(y_ref[...].astype(jnp.float32) > 0.0, dyb, 0.0)
        xh = ((x_ref[...].astype(jnp.float32) - mean_ref[...])
              * rstd_ref[...])
        return dyb, xh

    @pl.when(j < nb)
    def _reduce():
        dyb, xh = _dyp_xhat()
        sb[...] += jnp.sum(dyb, axis=0, keepdims=True)
        sxh[...] += jnp.sum(dyb * xh, axis=0, keepdims=True)

    @pl.when(j == nb - 1)
    def _grads():
        dgamma_ref[...] = sxh[...]
        dbeta_ref[...] = sb[...]

    @pl.when(j >= nb)
    def _dx():
        dyb, xh = _dyp_xhat()
        g = gamma_ref[...].astype(jnp.float32) * rstd_ref[...]
        dx = g * (dyb - sb[...] / n_total - xh * (sxh[...] / n_total))
        dx_ref[...] = dx.astype(dx_ref.dtype)


def _bn_backward(x2, gamma, mean, rstd, y2, dy2, act, interpret, bn):
    n, c = x2.shape
    nb = n // bn
    kern = functools.partial(_bn_bwd_kernel, nb=nb, n_total=float(n),
                             act=act)
    row_spec = pl.BlockSpec((bn, c), lambda j: (j % nb, 0))
    vec_spec = pl.BlockSpec((1, c), lambda j: (0, 0))
    ins = [x2, dy2] + ([y2] if act == "relu" else [])
    ins += [gamma.reshape(1, c), mean, rstd]
    in_specs = [row_spec, row_spec] + \
        ([row_spec] if act == "relu" else []) + [vec_spec] * 3
    dx, dgamma, dbeta = pl.pallas_call(
        kern,
        grid=(2 * nb,),
        in_specs=in_specs,
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, c), x2.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=interpret,
    )(*ins)
    return dx, dgamma, dbeta


# -- custom_vjp wrapper -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _bn_act_2d(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    y, mean, var = _bn_forward(x2, gamma, beta, eps, act, two_pass,
                               interpret, bn)
    return y, mean, var


def _bn_act_fwd(x2, gamma, beta, eps, act, two_pass, interpret, bn):
    y, mean, var = _bn_forward(x2, gamma, beta, eps, act, two_pass,
                               interpret, bn)
    rstd = jax.lax.rsqrt(var + eps)
    res = (x2, gamma, mean, rstd, y if act == "relu" else None)
    return (y, mean, var), res


def _bn_act_bwd(eps, act, two_pass, interpret, bn, res, cts):
    # cts = (dy, dmean, dvar); mean/var feed the running-stat EMA only
    # (carried state, never read by the loss), so their cotangents are
    # structurally zero and are dropped here — same contract as
    # flash_attention's lse output.
    x2, gamma, mean, rstd, y2 = res
    dy = cts[0]
    dx, dgamma, dbeta = _bn_backward(x2, gamma, mean, rstd, y2, dy, act,
                                     interpret, bn)
    return (dx, dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype))


_bn_act_2d.defvjp(_bn_act_fwd, _bn_act_bwd)


def fused_bn_act(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                 eps: float, act: str = "none", two_pass: bool = False,
                 interpret: Optional[bool] = None,
                 block_rows: int = 256):
    """Fused train-time batch norm (+ optional relu) over the trailing
    channel axis of an NHWC or flat node. Returns ``(y, mean, var)``
    with y in x.dtype and f32 stats, or ``None`` when unsupported
    (caller falls back to the jnp reference)."""
    if not HAVE_PALLAS or not supported_dtype(x):
        return None
    if x.ndim != 4 or act not in ("none", "relu"):
        return None
    c = x.shape[-1]
    n = x.size // c
    # keep ~2 row blocks + accumulators comfortably inside VMEM even
    # for wide flat nodes: shrink the row tile as C grows
    target = max(8, min(block_rows, (1 << 20) // max(4 * c, 1) // 8 * 8))
    bn = row_block(n, target, mult=sublane_mult(x))
    if bn is None or gamma.shape != (c,) or beta.shape != (c,):
        return None
    x2 = x.reshape(n, c)
    y, mean, var = _bn_act_2d(x2, gamma, beta, float(eps), act,
                              bool(two_pass), use_interpret(interpret), bn)
    return y.reshape(x.shape), mean.reshape(c), var.reshape(c)
