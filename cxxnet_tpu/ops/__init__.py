"""TPU-native operator library (Pallas kernels + jnp references).

The reference framework's op-extension mechanism is the hand-written
mshadow expression (e.g. InsanityPoolingExp with a custom Plan,
/root/reference/src/layer/insanity_pooling_layer-inl.hpp:13-100); the
TPU-native analog is a Pallas kernel paired with a jnp reference
implementation, validated by golden tests (the pairtest idea, SURVEY §4).
"""

from .attention import (
    attention_reference,
    chunked_attention,
    flash_attention,
    rope,
)
from .fused import kernels_active, resolve_mode
from .fused_epilogue import bias_act_reference, fused_bias_act
from .fused_lrn import fused_lrn, lrn_reference
from .fused_norm import bn_act_reference, fused_bn_act
from .fused_optim import fused_adam_apply, fused_sgd_apply

__all__ = [
    "attention_reference",
    "chunked_attention",
    "flash_attention",
    "rope",
    "kernels_active",
    "resolve_mode",
    "fused_bn_act",
    "bn_act_reference",
    "fused_lrn",
    "lrn_reference",
    "fused_bias_act",
    "bias_act_reference",
    "fused_sgd_apply",
    "fused_adam_apply",
]
