"""TPU-native operator library (Pallas kernels + jnp references).

The reference framework's op-extension mechanism is the hand-written
mshadow expression (e.g. InsanityPoolingExp with a custom Plan,
/root/reference/src/layer/insanity_pooling_layer-inl.hpp:13-100); the
TPU-native analog is a Pallas kernel paired with a jnp reference
implementation, validated by golden tests (the pairtest idea, SURVEY §4).
"""

from .attention import (
    attention_reference,
    chunked_attention,
    flash_attention,
    rope,
)

__all__ = [
    "attention_reference",
    "chunked_attention",
    "flash_attention",
    "rope",
]
