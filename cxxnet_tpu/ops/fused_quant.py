"""int8 inference kernels: static-scale activation quantization,
int8 x int8 -> int32 matmul/conv, fused dequant + bias + activation
epilogue.

Serving-side counterpart of the PTQ pass (quant/ptq.py): weights arrive
pre-quantized in the params tree (``wmat`` int8 + ``wmat_scale``
per-out-channel f32 + ``act_scale`` scalar f32), activations are
quantized on the fly against the calibrated static ``act_scale``, the
contraction runs int8 x int8 with an int32 accumulator (the MXU's
native low-precision path), and the epilogue folds dequantization,
bias-add and the graph-folded relu into the same pass. Inference-only
by design — there is no custom_vjp here (the PR-5 pattern: quantized
params never train), so the Pallas kernel is a plain forward
``pallas_call``.

Shape eligibility for the fused matmul kernel follows the int8 MXU
tiling (min tile 32 x 128): rows a multiple of 32, K and N multiples of
128. Anything else — and every convolution — runs the jnp reference
path, which lowers to XLA's own int8 dot/conv (exact same integer
math, so outputs are bit-identical across the two paths' dequant).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl


def quantize_act(x: jax.Array, act_scale) -> jax.Array:
    """Static-scale activation quantization: f32 -> int8 against the
    calibrated per-layer clip value. Symmetric: +-act_scale maps to
    +-127; values beyond the calibrated range saturate (that is the
    percentile-clip contract — rare outliers trade for resolution)."""
    s = jnp.asarray(act_scale, jnp.float32)
    q = jnp.round(jnp.clip(x.astype(jnp.float32) / s, -1.0, 1.0) * 127.0)
    return q.astype(jnp.int8)


def dequant_factor(w_scale: jax.Array, act_scale) -> jax.Array:
    """Per-out-channel f32 factor turning the int32 accumulator back
    into real units: acc * (act_scale/127) * w_scale."""
    return w_scale.astype(jnp.float32) * (
        jnp.asarray(act_scale, jnp.float32) / 127.0)


def _epilogue(acc_i32: jax.Array, factor: jax.Array,
              bias: Optional[jax.Array], act: str) -> jax.Array:
    y = acc_i32.astype(jnp.float32) * factor
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if act == "relu":
        y = jax.nn.relu(y)
    return y


# -- fused Pallas matmul ------------------------------------------------------

def _q_mm_kernel(*refs, act, has_bias):
    if has_bias:
        x_ref, w_ref, f_ref, b_ref, y_ref = refs
    else:
        x_ref, w_ref, f_ref, y_ref = refs
        b_ref = None
    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * f_ref[...]
    if has_bias:
        y = y + b_ref[...]
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y


def _q_mm_pallas(xq, wq, factor, bias, act, bm, bn, interpret):
    m, k = xq.shape
    n = wq.shape[1]
    has_bias = bias is not None
    in_specs = [
        pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
        pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        pl.BlockSpec((1, bn), lambda i, j: (0, j)),
    ]
    args = [xq, wq, factor.reshape(1, n)]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j: (0, j)))
        args.append(bias.astype(jnp.float32).reshape(1, n))
    return pl.pallas_call(
        functools.partial(_q_mm_kernel, act=act, has_bias=has_bias),
        grid=(m // bm, n // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(*args)


def _mm_blocks(m: int, k: int, n: int) -> Optional[Tuple[int, int]]:
    """(bm, bn) for the fused int8 matmul, or None when the shape does
    not tile the int8 MXU layout (min tile 32 x 128)."""
    if k % 128 or n % 128:
        return None
    bm = row_block(m, 256, mult=32)
    bn = row_block(n, 512, mult=128)
    if bm is None or bn is None:
        return None
    return bm, bn


def int8_matmul(x: jax.Array, wq: jax.Array, w_scale: jax.Array,
                act_scale, bias: Optional[jax.Array] = None,
                act: str = "none", *, fused: bool = False,
                spmd: Optional[FusedSpmd] = None,
                interpret: Optional[bool] = None) -> jax.Array:
    """Quantized linear: f32 ``x`` (m, k) against pre-quantized ``wq``
    (k, n) int8 with per-out-channel ``w_scale`` (n,). Activations are
    quantized against the static ``act_scale``; output is f32 after the
    fused dequant (+bias, +act) epilogue. ``fused=True`` attempts the
    Pallas kernel (falling back to the bit-identical jnp reference on
    ineligible shapes); ``spmd`` islands the kernel over the batch axis
    with weights/scales replicated, matching the PR-9 plumbing."""
    xq = quantize_act(x, act_scale)
    factor = dequant_factor(w_scale, act_scale)
    if fused and HAVE_PALLAS and act in ("none", "relu"):
        m = xq.shape[0]
        m_local = m
        if spmd is not None:
            if not batch_divisible(spmd, m):
                note_fallback("quant_batch_indivisible")
                spmd = None
            else:
                m_local = m // spmd.n_shards
        blocks = _mm_blocks(m_local, xq.shape[1], wq.shape[1])
        if blocks is not None:
            bm, bn = blocks
            itp = use_interpret(interpret)
            if spmd is not None:
                return island(
                    spmd,
                    lambda xl, wl, fl, bl: _q_mm_pallas(
                        xl, wl, fl, bl, act, bm, bn, itp),
                    in_batch=(True, False, False, False),
                    out_batch=True)(xq, wq, factor, bias)
            return _q_mm_pallas(xq, wq, factor, bias, act, bm, bn, itp)
        note_fallback("quant_mm_shape")
    acc = lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return _epilogue(acc, factor, bias, act)


def int8_conv(x: jax.Array, wq: jax.Array, w_scale: jax.Array,
              act_scale, bias: Optional[jax.Array] = None,
              act: str = "none", *,
              strides: Tuple[int, int] = (1, 1),
              padding=((0, 0), (0, 0)),
              groups: int = 1) -> jax.Array:
    """Quantized convolution: f32 NHWC ``x`` against pre-quantized HWIO
    ``wq`` int8 with per-out-channel ``w_scale``. The contraction runs
    on XLA's int8 conv lowering (int32 accumulator); dequant + bias +
    act fuse into the epilogue. No Pallas variant — the direct conv
    already hits the MXU via XLA, and the epilogue is elementwise."""
    xq = quantize_act(x, act_scale)
    acc = lax.conv_general_dilated(
        xq, wq,
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        preferred_element_type=jnp.int32)
    return _epilogue(acc, dequant_factor(w_scale, act_scale), bias, act)
