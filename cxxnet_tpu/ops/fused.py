"""Shared plumbing for the fused Pallas kernel suite (doc/tasks.md
"Fused kernels").

Selection contract — the one rule every fused op follows:

* ``fused_kernels = auto`` (default): kernels are selected on TPU
  backends only; every other backend runs the jnp reference the layer
  already shipped. This is the production setting — the flagship bench
  is HBM-bound (BENCH_r02–r04: ~100–105% of the bandwidth roofline at
  MFU ~28%), and the fused kernels exist to move fewer HBM bytes per
  step, which only a real TPU pays for.
* ``fused_kernels = 1``: kernels are selected everywhere; off-TPU they
  run under ``interpret=True`` (the flash-attention testing pattern —
  the SAME kernel code is exercised by CPU tests and smokes).
* ``fused_kernels = 0``: jnp references everywhere — the escape hatch.
* env ``CXXNET_FUSED_KERNELS`` overrides the config knob with the same
  values (ops-level kill switch that needs no config edit).

Gating beyond the knob (callers, not this module): a ``pallas_call``
is an opaque custom call the GSPMD partitioner cannot shard, so on a
multi-device mesh every fused op runs inside a fully-MANUAL
``shard_map`` island (:func:`island`) whose in/out specs shard the
batch dim over the data axis — per-op collectives (the fused BN's
moment psum, the epilogue's dbias psum) make the mesh math match the
GSPMD jnp references exactly (sync-BN stays sync-BN). The trainer
hands the mesh context to the ops as a :class:`FusedSpmd` via
``Network.fused_spmd`` / ``Optimizer.fused_spmd``; topologies the
islands do not cover (pipeline stages, sp x tp) still clear the gate,
now with a one-time warning and a
``cxxnet_fused_fallback_total{reason}`` counter (:func:`note_fallback`)
instead of a silent slow path.

Every fused op returns ``None`` for unsupported shapes/dtypes and the
caller falls back to its reference implementation, so selection is
always safe — never an error.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional, Sequence, Union

import jax

from ..config import parse_fused_mode

try:  # same lazy-import guard as ops/attention.py: CPU-only installs
    from jax.experimental import pallas as pl           # noqa: F401
    from jax.experimental.pallas import tpu as pltpu    # noqa: F401
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

#: dtypes the fused kernels accept as activation inputs; everything is
#: accumulated in f32 inside the kernels regardless.
SUPPORTED_DTYPES = ("float32", "bfloat16", "float16")

#: canonicalize a ``fused_kernels`` value -> auto|on|off (the config
#: layer owns the grammar; re-exported here for the ops-side callers)
resolve_mode = parse_fused_mode


def kernels_active(mode: str) -> bool:
    """Trace-time selection decision for a resolved mode string. The
    ``CXXNET_FUSED_KERNELS`` env var wins over the config knob."""
    env = os.environ.get("CXXNET_FUSED_KERNELS", "")
    if env:
        mode = resolve_mode(env)
    if mode == "off" or not HAVE_PALLAS:
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


@dataclasses.dataclass(frozen=True)
class FusedSpmd:
    """Mesh context for shard_map-wrapped fused kernels: the mesh and
    the axis the batch's leading dim is sharded over. Hashable (Mesh
    hashes by device assignment) so it can ride custom_vjp
    nondiff_argnums."""
    mesh: Any                 # jax.sharding.Mesh
    batch_axis: str = "data"

    @property
    def n_shards(self) -> int:
        return int(self.mesh.shape[self.batch_axis])


def island(spmd: FusedSpmd, fn, in_batch: Sequence[bool],
           out_batch: Union[bool, Sequence[bool]]):
    """Wrap ``fn`` in a fully-manual shard_map over EVERY mesh axis
    (via parallel/compat.py, so jax-0.4.x spells it the same way):
    args flagged True in ``in_batch`` shard their leading dim over
    ``spmd.batch_axis``, the rest replicate; ``out_batch`` likewise
    for the outputs (a bare bool for a single output). Inside the
    island GSPMD never sees the pallas_call — the body is manual —
    and any cross-shard reduction is the body's own explicit psum."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map
    bspec = P(spmd.batch_axis)
    in_specs = tuple(bspec if b else P() for b in in_batch)
    if isinstance(out_batch, bool):
        out_specs: Any = bspec if out_batch else P()
    else:
        out_specs = tuple(bspec if b else P() for b in out_batch)
    return shard_map(fn, mesh=spmd.mesh, in_specs=in_specs,
                     out_specs=out_specs,
                     axis_names=set(spmd.mesh.axis_names))


def batch_divisible(spmd: Optional[FusedSpmd], leading: int) -> bool:
    """Whether the batch's leading dim splits evenly over the island's
    batch axis (callers fall back to their reference otherwise)."""
    return spmd is None or (spmd.n_shards > 0
                            and leading % spmd.n_shards == 0)


#: reasons already warned about (print once per process, count always)
_FALLBACK_WARNED = set()


def note_fallback(reason: str, warn: Optional[str] = None) -> None:
    """Record a fused-path fallback: always bumps
    ``cxxnet_fused_fallback_total{reason}`` in the telemetry registry
    (visible in /metrics and fleet snapshots), and prints ``warn``
    once per process — a mesh run that silently loses its fused hot
    path is exactly the quiet misconfiguration telemetry exists for."""
    try:
        from ..telemetry.registry import get_registry
        get_registry().counter(
            "cxxnet_fused_fallback_total",
            "fused kernel suite fallbacks to the reference path, "
            "by reason", labels=("reason",)).labels(reason).inc()
    except Exception:
        pass
    if warn and reason not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(reason)
        print(f"fused_kernels: {warn} (reason={reason}; counted in "
              "cxxnet_fused_fallback_total)", flush=True)


def use_interpret(interpret: Optional[bool]) -> bool:
    """interpret=None auto-selects interpreter mode off-TPU — the same
    kernel is exercised in CPU tests (flash_attention's contract)."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def row_block(n: int, target: int = 256, mult: int = 8) -> Optional[int]:
    """Largest row-block size that (a) divides ``n`` exactly, (b) is a
    multiple of ``mult`` (the sublane tile: 8 for f32, 16 for
    bf16/f16 — see sublane_mult), and (c) is <= ``target`` (VMEM
    residency cap). ``None`` when ``n`` has no such divisor — the
    caller falls back to its jnp reference (no remainder masking:
    unsupported is cheaper than wrong)."""
    if n <= 0 or n % mult:
        return None
    best = None
    for b in range(mult, min(target, n) + 1, mult):
        if n % b == 0:
            best = b
    return best


def sublane_mult(x: jax.Array) -> int:
    """Min sublane tile multiple for this dtype's TPU layout: (8, 128)
    for f32, (16, 128) for the 16-bit floats."""
    import jax.numpy as jnp
    return 8 if jnp.dtype(x.dtype).itemsize == 4 else 16


def supported_dtype(x: jax.Array) -> bool:
    import jax.numpy as jnp
    return jnp.dtype(x.dtype).name in SUPPORTED_DTYPES
