"""Shared plumbing for the fused Pallas kernel suite (doc/tasks.md
"Fused kernels").

Selection contract — the one rule every fused op follows:

* ``fused_kernels = auto`` (default): kernels are selected on TPU
  backends only; every other backend runs the jnp reference the layer
  already shipped. This is the production setting — the flagship bench
  is HBM-bound (BENCH_r02–r04: ~100–105% of the bandwidth roofline at
  MFU ~28%), and the fused kernels exist to move fewer HBM bytes per
  step, which only a real TPU pays for.
* ``fused_kernels = 1``: kernels are selected everywhere; off-TPU they
  run under ``interpret=True`` (the flash-attention testing pattern —
  the SAME kernel code is exercised by CPU tests and smokes).
* ``fused_kernels = 0``: jnp references everywhere — the escape hatch.
* env ``CXXNET_FUSED_KERNELS`` overrides the config knob with the same
  values (ops-level kill switch that needs no config edit).

Gating beyond the knob (callers, not this module): fused ops are
single-device only — a ``pallas_call`` is an opaque custom call the
GSPMD partitioner cannot shard, and the fused BN's moments would be
shard-local where the jnp path's ``jnp.mean`` is a sync-BN collective.
The trainer clears ``Network.fused_single_device`` /
``Optimizer.fused_ok`` on multi-device meshes.

Every fused op returns ``None`` for unsupported shapes/dtypes and the
caller falls back to its reference implementation, so selection is
always safe — never an error.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..config import parse_fused_mode

try:  # same lazy-import guard as ops/attention.py: CPU-only installs
    from jax.experimental import pallas as pl           # noqa: F401
    from jax.experimental.pallas import tpu as pltpu    # noqa: F401
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

#: dtypes the fused kernels accept as activation inputs; everything is
#: accumulated in f32 inside the kernels regardless.
SUPPORTED_DTYPES = ("float32", "bfloat16", "float16")

#: canonicalize a ``fused_kernels`` value -> auto|on|off (the config
#: layer owns the grammar; re-exported here for the ops-side callers)
resolve_mode = parse_fused_mode


def kernels_active(mode: str) -> bool:
    """Trace-time selection decision for a resolved mode string. The
    ``CXXNET_FUSED_KERNELS`` env var wins over the config knob."""
    env = os.environ.get("CXXNET_FUSED_KERNELS", "")
    if env:
        mode = resolve_mode(env)
    if mode == "off" or not HAVE_PALLAS:
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def use_interpret(interpret: Optional[bool]) -> bool:
    """interpret=None auto-selects interpreter mode off-TPU — the same
    kernel is exercised in CPU tests (flash_attention's contract)."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def row_block(n: int, target: int = 256, mult: int = 8) -> Optional[int]:
    """Largest row-block size that (a) divides ``n`` exactly, (b) is a
    multiple of ``mult`` (the sublane tile: 8 for f32, 16 for
    bf16/f16 — see sublane_mult), and (c) is <= ``target`` (VMEM
    residency cap). ``None`` when ``n`` has no such divisor — the
    caller falls back to its jnp reference (no remainder masking:
    unsupported is cheaper than wrong)."""
    if n <= 0 or n % mult:
        return None
    best = None
    for b in range(mult, min(target, n) + 1, mult):
        if n % b == 0:
            best = b
    return best


def sublane_mult(x: jax.Array) -> int:
    """Min sublane tile multiple for this dtype's TPU layout: (8, 128)
    for f32, (16, 128) for the 16-bit floats."""
    import jax.numpy as jnp
    return 8 if jnp.dtype(x.dtype).itemsize == 4 else 16


def supported_dtype(x: jax.Array) -> bool:
    import jax.numpy as jnp
    return jnp.dtype(x.dtype).name in SUPPORTED_DTYPES
