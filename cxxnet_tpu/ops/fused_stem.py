"""Fused uint8 stem decode-normalize: Pallas TPU kernel + jnp reference.

The ``device_normalize`` input path (doc/e2e_input.md) ships uint8
batches (4x smaller H2D) and normalizes on-device — but as a SEPARATE
jitted dispatch that reads the uint8 batch and writes a full fp32 copy
the train step then re-reads. Per pixel that is 1 (u8 read) + 4 (f32
write) + 4 (f32 step read) = 9 bytes before the stem conv sees anything.

This op is the in-step replacement (trainer ``input_fold``): the uint8
batch enters the train step directly and the cast/mean-subtract/scale
happens inside the compiled step, emitting the stem conv's input in the
compute dtype — 1 (u8 read) + compute-dtype write, with XLA free to fuse
the write into the space-to-depth producer chain (layers/conv.py). The
fp32 round-trip of the whole input batch is gone; at flagship shape
(256x224x224x3) that is ~310 MB of HBM traffic per step.

Two implementations, selected by the caller's ``fused`` flag:

* :func:`decode_normalize_reference` — plain jnp; inside jit XLA fuses
  it into the consumer. This is the default (and the escape hatch).
* :func:`fused_decode_normalize` — one Pallas streaming pass over the
  batch viewed as (rows, H*W*C) with the mean tiled/flattened to a
  single (1, H*W*C) row; returns None for unsupported shapes.

Numerics: the fold computes in f32 and casts ONCE to the compute dtype
— under an fp32 policy this is bit-identical to the eager
``_device_normalize`` path; under bf16/fp16 the input enters the model
already rounded to the compute dtype, which is exactly where the
layers' own ``astype(ctx.compute_dtype)`` puts it one op later.

No custom_vjp: the data path carries no gradient (the step
differentiates w.r.t. params only), so the kernel never sits on a
tangent path.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl


def decode_normalize_reference(x: jax.Array, mean: Optional[jax.Array],
                               factor, out_dtype: Any) -> jax.Array:
    """Golden jnp implementation — Trainer._device_normalize's math
    (cast, subtract mean, scale) with the output in ``out_dtype``.
    ``mean`` broadcasts over the trailing axes: per-channel (C,) or a
    mean image (H, W, C). ``factor`` may be a traced scalar."""
    y = x.astype(jnp.float32)
    if mean is not None:
        y = y - mean
    y = y * factor
    return y.astype(out_dtype)


def _stem_kernel(*refs, has_mean):
    if has_mean:
        x_ref, mean_ref, f_ref, y_ref = refs
    else:
        x_ref, f_ref, y_ref = refs
        mean_ref = None
    y = x_ref[...].astype(jnp.float32)
    if mean_ref is not None:
        y = y - mean_ref[...]
    y = y * f_ref[...]
    y_ref[...] = y.astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret",
                                             "rb", "cb"))
def _stem_call(x2, mean_row, factor, out_dtype, interpret, rb, cb):
    n, cols = x2.shape
    has_mean = mean_row is not None
    kern = functools.partial(_stem_kernel, has_mean=has_mean)
    row_spec = pl.BlockSpec((rb, cb), lambda i, j: (i, j))
    vec_spec = pl.BlockSpec((1, cb), lambda i, j: (0, j))
    scal_spec = pl.BlockSpec((1, 1), lambda i, j: (0, 0))
    ins = [x2] + ([mean_row] if has_mean else []) + \
        [factor.reshape(1, 1)]
    in_specs = [row_spec] + ([vec_spec] if has_mean else []) + [scal_spec]
    return pl.pallas_call(
        kern,
        grid=(n // rb, cols // cb),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((n, cols), out_dtype),
        interpret=interpret,
    )(*ins)


def _col_block(cols: int, target: int = 2048, mult: int = 128
               ) -> Optional[int]:
    """Largest divisor of ``cols`` that is a multiple of ``mult`` (the
    lane tile) and <= target; None when there is none."""
    if cols <= 0 or cols % mult:
        return None
    best = None
    for b in range(mult, min(target, cols) + 1, mult):
        if cols % b == 0:
            best = b
    return best


def fused_decode_normalize(x: jax.Array, mean: Optional[jax.Array],
                           factor, out_dtype: Any,
                           interpret: Optional[bool] = None,
                           spmd: Optional[FusedSpmd] = None
                           ) -> Optional[jax.Array]:
    """One streaming Pallas pass: uint8 NHWC batch -> normalized
    compute-dtype batch. ``mean`` is None, per-channel (C,), or a mean
    image (H, W, C); ``factor`` a scalar (python or traced). Returns
    None when the shape is unsupported (caller uses the jnp
    reference). With ``spmd`` the pass runs as a shard_map island over
    the batch dim (pure data path — no collectives, no vjp)."""
    if not HAVE_PALLAS or x.dtype != jnp.uint8 or x.ndim != 4:
        return None
    b, h, w, c = x.shape
    cols = h * w * c
    b_local = b
    if spmd is not None:
        if not batch_divisible(spmd, b):
            note_fallback("stem_batch_indivisible")
            return None
        b_local = b // spmd.n_shards
    # batch rows: uint8 tiles pack (32, 128); accept the f32 sublane (8)
    # as a fallback so small CPU-test batches still exercise the kernel
    # in interpret mode
    rb = row_block(b_local, 128, mult=32) or row_block(b_local, 128,
                                                       mult=8)
    cb = _col_block(cols)
    if rb is None or cb is None:
        if spmd is not None:
            note_fallback("stem_shape")
        return None
    if mean is not None:
        mean = jnp.asarray(mean, jnp.float32)
        if mean.shape == (c,):
            # per-channel mean -> one flattened (1, H*W*C) row; the tile
            # is tiny (<=600 KB at flagship shape) and shared by every
            # batch row
            mean_row = jnp.tile(mean, h * w).reshape(1, cols)
        elif mean.shape == (h, w, c):
            mean_row = mean.reshape(1, cols)
        else:
            return None
    else:
        mean_row = None
    factor = jnp.asarray(factor, jnp.float32)
    itp = use_interpret(interpret)
    if spmd is not None:
        # mean_row/factor may be traced step arguments — explicit
        # island inputs (replicated), never closure captures
        if mean_row is not None:
            def local(xl, mr, f):
                y2l = _stem_call(xl.reshape(-1, cols), mr,
                                 f, jnp.dtype(out_dtype), itp, rb, cb)
                return y2l.reshape(xl.shape)
            return island(spmd, local, in_batch=(True, False, False),
                          out_batch=True)(x, mean_row, factor)

        def local(xl, f):
            y2l = _stem_call(xl.reshape(-1, cols), None, f,
                             jnp.dtype(out_dtype), itp, rb, cb)
            return y2l.reshape(xl.shape)
        return island(spmd, local, in_batch=(True, False),
                      out_batch=True)(x, factor)
    y2 = _stem_call(x.reshape(b, cols), mean_row, factor,
                    jnp.dtype(out_dtype), itp, rb, cb)
    return y2.reshape(b, h, w, c)


def decode_normalize(x: jax.Array, mean: Optional[jax.Array], factor,
                     out_dtype: Any, fused: bool = False,
                     spmd: Optional[FusedSpmd] = None) -> jax.Array:
    """Dispatcher the trainer's folded step calls: the Pallas kernel
    when the fused suite is active (and the shape qualifies), else the
    jnp reference — both inside the compiled train step."""
    if fused:
        y = fused_decode_normalize(x, mean, factor, out_dtype, spmd=spmd)
        if y is not None:
            return y
    return decode_normalize_reference(x, mean, factor, out_dtype)
