"""Fused bias + activation epilogue for conv/fullc outputs.

The cxxnet reference hand-fused bias-add and activation into its conv
kernels' epilogues; here the conv/matmul itself stays on XLA's MXU
lowering (it wins there) and only the epilogue — bias broadcast-add
plus the (graph-folded, see graph.act_fusion_plan) relu — runs as one
Pallas kernel: one streaming read of the conv output, one write, with
the backward fusing the dx mask and the per-channel dbias reduction
into a single pass (the autodiff graph otherwise schedules the relu
mask, the dbias reduce, and the dx select as separate HBM-visible
values in cost_analysis' accounting).

Views everything as (N, C) rows like the other fused ops. ``act`` may
be "relu" or "none"; ``bias`` may be None (act-only epilogue — the
no_bias conv -> relu case). Returns ``None`` when unsupported or when
there is nothing to fuse (no bias AND no act).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .fused import (HAVE_PALLAS, FusedSpmd, batch_divisible, island,
                    note_fallback, row_block, sublane_mult,
                    supported_dtype, use_interpret)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def bias_act_reference(x: jax.Array, bias: Optional[jax.Array],
                       act: str = "none") -> jax.Array:
    """Golden jnp implementation, matching the layers' existing math
    (bias cast to the activation dtype before the add)."""
    y = x if bias is None else x + bias.astype(x.dtype)
    if act == "relu":
        y = jax.nn.relu(y)
    return y


def _epi_fwd_kernel(*refs, act, has_bias):
    if has_bias:
        x_ref, b_ref, y_ref = refs
        y = x_ref[...] + b_ref[...].astype(x_ref.dtype)
    else:
        x_ref, y_ref = refs
        y = x_ref[...]
    if act == "relu":
        y = jnp.maximum(y, 0)
    y_ref[...] = y


def _epi_bwd_kernel(*refs, act, has_bias, nb):
    """dx per block; dbias accumulates across the (sequential) grid in
    scratch and lands in its (1, C) output at the last step."""
    if has_bias:
        y_ref, dy_ref, dx_ref, db_ref, acc = refs
    else:
        y_ref, dy_ref, dx_ref = refs
        db_ref = acc = None
    j = pl.program_id(0)
    dyb = dy_ref[...]
    if act == "relu":
        dyb = jnp.where(y_ref[...] > 0, dyb, 0)
    dx_ref[...] = dyb
    if has_bias:
        @pl.when(j == 0)
        def _init():
            acc[...] = jnp.zeros_like(acc)
        acc[...] += jnp.sum(dyb.astype(jnp.float32), axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _finish():
            db_ref[...] = acc[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _epi_act_2d(x2, act, interpret, bn):
    """act-only epilogue (no bias)."""
    n, c = x2.shape
    return pl.pallas_call(
        functools.partial(_epi_fwd_kernel, act=act, has_bias=False),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
        interpret=interpret,
    )(x2)


def _epi_act_fwd(x2, act, interpret, bn):
    y = _epi_act_2d(x2, act, interpret, bn)
    return y, y


def _epi_act_bwd(act, interpret, bn, y, dy):
    n, c = y.shape
    dx = pl.pallas_call(
        functools.partial(_epi_bwd_kernel, act=act, has_bias=False,
                          nb=n // bn),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((bn, c), lambda j: (j, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), y.dtype),
        interpret=interpret,
    )(y, dy)
    return (dx,)


_epi_act_2d.defvjp(_epi_act_fwd, _epi_act_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _epi_bias_2d(x2, bias, act, interpret, bn):
    n, c = x2.shape
    return pl.pallas_call(
        functools.partial(_epi_fwd_kernel, act=act, has_bias=True),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((1, c), lambda j: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
        interpret=interpret,
    )(x2, bias.reshape(1, c))


def _epi_bias_fwd(x2, bias, act, interpret, bn):
    y = _epi_bias_2d(x2, bias, act, interpret, bn)
    return y, (y, bias)


def _epi_bias_bwd(act, interpret, bn, res, dy):
    y, bias = res
    n, c = y.shape
    dx, db = pl.pallas_call(
        functools.partial(_epi_bwd_kernel, act=act, has_bias=True,
                          nb=n // bn),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                  pl.BlockSpec((bn, c), lambda j: (j, 0))],
        out_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                   pl.BlockSpec((1, c), lambda j: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, c), y.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32)],
        interpret=interpret,
    )(y, dy)
    return dx, db.reshape(bias.shape).astype(bias.dtype)


_epi_bias_2d.defvjp(_epi_bias_fwd, _epi_bias_bwd)


# -- mesh (shard_map island) variant ------------------------------------------
#
# Bias + act over a batch-sharded node: fwd/bwd pallas calls each run
# inside their own fully-manual island (custom_vjp OUTSIDE the
# shard_map), and the only collective is the backward's dbias psum
# over the data axis — a replicated bias's gradient is the sum of the
# shard-local column reductions. Act-only epilogues have no
# replicated operand at all and simply island-wrap the existing
# custom_vjp (all specs batch-sharded, so the shard_map transpose is
# collective-free and exact).

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _epi_bias_mesh(x, bias, act, interpret, bn, spmd):
    c = x.shape[-1]
    return island(
        spmd, lambda xl, bl: _epi_bias_2d(
            xl.reshape(-1, c), bl, act, interpret, bn
        ).reshape(xl.shape),
        in_batch=(True, False), out_batch=True)(x, bias)


def _epi_bias_mesh_fwd(x, bias, act, interpret, bn, spmd):
    y = _epi_bias_mesh(x, bias, act, interpret, bn, spmd)
    return y, (y, bias)


def _epi_bias_mesh_bwd(act, interpret, bn, spmd, res, dy):
    y, bias = res
    c = y.shape[-1]

    def local(yl, dyl):
        n = yl.size // c
        dx2, db = pl.pallas_call(
            functools.partial(_epi_bwd_kernel, act=act, has_bias=True,
                              nb=n // bn),
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                      pl.BlockSpec((bn, c), lambda j: (j, 0))],
            out_specs=[pl.BlockSpec((bn, c), lambda j: (j, 0)),
                       pl.BlockSpec((1, c), lambda j: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, c), yl.dtype),
                       jax.ShapeDtypeStruct((1, c), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((1, c), jnp.float32)],
            interpret=interpret,
        )(yl.reshape(n, c), dyl.reshape(n, c))
        db = jax.lax.psum(db, spmd.batch_axis)
        return dx2.reshape(yl.shape), db
    dx, db = island(spmd, local, in_batch=(True, True),
                    out_batch=(True, False))(y, dy)
    return dx, db.reshape(bias.shape).astype(bias.dtype)


_epi_bias_mesh.defvjp(_epi_bias_mesh_fwd, _epi_bias_mesh_bwd)


def fused_bias_act(x: jax.Array, bias: Optional[jax.Array],
                   act: str = "none", interpret: Optional[bool] = None,
                   block_rows: int = 512,
                   spmd: Optional[FusedSpmd] = None):
    """Fused epilogue on an NHWC/flat node's trailing channel axis.
    Returns y (x.dtype) or ``None`` when unsupported / nothing to
    fuse. With ``spmd`` the kernels run as shard_map islands on the
    mesh (dbias psum'd over the data axis in the backward)."""
    if not HAVE_PALLAS or not supported_dtype(x):
        return None
    if x.ndim != 4 or act not in ("none", "relu"):
        return None
    if bias is None and act == "none":
        return None                      # nothing to fuse
    c = x.shape[-1]
    n = x.size // c
    if spmd is not None:
        if not batch_divisible(spmd, x.shape[0]):
            note_fallback("epilogue_batch_indivisible")
            return None
        n_local = n // spmd.n_shards
    else:
        n_local = n
    target = max(8, min(block_rows, (1 << 20) // max(4 * c, 1) // 8 * 8))
    bn = row_block(n_local, target, mult=sublane_mult(x))
    if bn is None or (bias is not None and bias.shape != (c,)):
        if spmd is not None:
            note_fallback("epilogue_shape")
        return None
    itp = use_interpret(interpret)
    if spmd is not None:
        if bias is None:
            return island(
                spmd, lambda xl: _epi_act_2d(
                    xl.reshape(-1, c), act, itp, bn).reshape(xl.shape),
                in_batch=(True,), out_batch=True)(x)
        return _epi_bias_mesh(x, bias, act, itp, bn, spmd)
    x2 = x.reshape(n, c)
    if bias is None:
        y = _epi_act_2d(x2, act, itp, bn)
    else:
        y = _epi_bias_2d(x2, bias, act, itp, bn)
    return y.reshape(x.shape)
